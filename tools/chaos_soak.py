#!/usr/bin/env python
"""Randomized (seeded) multi-process chaos soak over the elastic
training stack — the acceptance drill for docs/fault_tolerance.md:

    python tools/chaos_soak.py --seed 7 --events 4 --workdir /tmp/soak

One standalone MASTER process (``python -m paddle_tpu.dist.master``,
FileStore snapshot) feeds one WORKER process (this script, ``--role
worker``) training a deterministic model through ``master_reader`` with
background checkpointing and ``--auto_resume`` semantics. A seeded
schedule then commits crimes:

- ``kill_worker``  — SIGKILL the trainer at a random moment
- ``kill_master``  — SIGKILL the master; restart it (same port, same
                     snapshot); the worker's client redials
- ``corrupt``      — truncate the newest checkpoint generation on disk
- ``plan_kill``    — re-arm the worker's env FaultPlan to die AT a
                     specific step (deterministic in-process exit)

plus a standing low-rate message-drop/delay FaultPlan in the worker's
env (``PADDLE_TPU_CHAOS_PLAN``). Dead processes are restarted with
zero manual intervention until the worker completes its pass budget.

The PASS bar: the chaos run's final parameters are BITWISE equal to a
clean run's (same seed, no faults) — exact resume + lease-based task
recovery + commit-after-durable-checkpoint mean no kill timing, master
death, corruption or message loss may perturb the trajectory. Exits 0
on equality; prints one JSON line either way.

Tier-1 keeps the fast in-process chaos subset (tests/test_chaos.py);
this soak runs as tests/test_chaos_soak.py, marked ``slow`` + ``chaos``.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import signal
import socket
import subprocess
import sys
import time

# ---------------------------------------------------------------- model
# (worker-role imports of jax/paddle_tpu happen inside worker_main so
# the controller stays import-light)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WIDTH, CLASSES, B = 8, 3, 8


def _child_env(extra=None):
    """Env for spawned children: repo root on PYTHONPATH (running this
    file by path puts ``tools/`` on sys.path, not the repo)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    if extra:
        env.update(extra)
    return env


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def worker_main(args) -> int:
    os.environ.setdefault("XLA_FLAGS", "")
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax.numpy as jnp

    from paddle_tpu.config import dsl
    from paddle_tpu.core.argument import Argument
    from paddle_tpu.dist.checkpoint import Checkpointer
    from paddle_tpu.dist.master import MasterClient, master_reader
    from paddle_tpu.optim import Adam
    from paddle_tpu.testing import chaos
    from paddle_tpu.trainer import SGD

    chaos.install_from_env()

    done_marker = os.path.join(args.workdir, "DONE")
    if os.path.exists(done_marker):
        return 0

    rng = np.random.RandomState(args.seed)
    X = rng.randn(args.batches * B, WIDTH).astype(np.float32)
    W = rng.randn(WIDTH, CLASSES)
    Y = np.argmax(X @ W, axis=1).astype(np.int32)
    feeds = [{"x": Argument(value=jnp.asarray(X[i:i + B])),
              "label": Argument(value=jnp.asarray(Y[i:i + B]))}
             for i in range(0, args.batches * B, B)]

    dsl.reset()
    x = dsl.data(name="x", size=WIDTH)
    lbl = dsl.data(name="label", size=CLASSES)
    h = dsl.fc(input=x, size=WIDTH, act="tanh")
    h = dsl.dropout(input=h, rate=0.25)
    out = dsl.fc(input=h, size=CLASSES, act="softmax")
    cost = dsl.classification_cost(input=out, label=lbl)
    trainer = SGD(cost=cost, update_equation=Adam(learning_rate=3e-3),
                  seed=args.seed)

    host, _, port = args.master.rpartition(":")
    client = MasterClient((host, int(port)), trainer_id="trainer-0",
                          retries=200, retry_delay=0.02, backoff_cap=0.5,
                          heartbeat_s=0.5)
    client.set_dataset(list(range(args.batches)))

    def load_chunk(i):
        yield feeds[int(i)]

    reader = master_reader(client, load_chunk)
    ck = Checkpointer(os.path.join(args.workdir, "ckpt"),
                      saving_period=1, saving_period_by_batches=2,
                      background=True)
    trainer.train(reader, num_passes=args.passes, checkpointer=ck)

    params = {k: np.asarray(jax.device_get(v))
              for k, v in trainer._params_for_save().items()}
    tmp = args.out + ".tmp.npz"  # savez appends .npz to bare names
    np.savez(tmp, **params)
    os.replace(tmp, args.out)
    with open(done_marker, "w") as f:
        f.write("ok")
    client.close()
    return 0


# ----------------------------------------------------------- controller

class _Procs:
    def __init__(self):
        self.master = None
        self.worker = None

    def kill_all(self):
        for p in (self.master, self.worker):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait()


def _spawn_master(port, store, log):
    return subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.dist.master",
         "--port", str(port), "--store", store,
         "--timeout_s", "10", "--failure_max", "1000"],
        env=_child_env(), stdout=log, stderr=log)


def _spawn_worker(args, port, workdir, out, plan_json, log):
    env = _child_env({"PADDLE_TPU_MASTER": f"127.0.0.1:{port}"})
    if plan_json:
        env["PADDLE_TPU_CHAOS_PLAN"] = plan_json
    else:
        env.pop("PADDLE_TPU_CHAOS_PLAN", None)
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--role", "worker",
         "--seed", str(args.seed), "--passes", str(args.passes),
         "--batches", str(args.batches), "--workdir", workdir,
         "--master", f"127.0.0.1:{port}", "--out", out],
        env=env, stdout=log, stderr=log)


def _run_to_completion(args, tag, chaos_events, log_path):
    """One full job (master + worker [+ scheduled faults]) to DONE;
    returns the final-params path."""
    workdir = os.path.join(args.workdir, tag)
    os.makedirs(workdir, exist_ok=True)
    out = os.path.join(workdir, "final_params.npz")
    store = os.path.join(workdir, "master.snap")
    port = _free_port()
    schedule = random.Random(args.seed * 7919 + (1 if chaos_events else 0))
    base_plan = None
    if chaos_events:
        base_plan = json.dumps({"seed": args.seed, "faults": [
            {"type": "drop", "site": "msg_recv", "rate": 0.03},
            {"type": "delay", "site": "msg_send", "every": 13,
             "seconds": 0.005}]})
    procs = _Procs()
    events = []
    deadline = time.monotonic() + args.timeout
    log = open(log_path, "ab")
    try:
        procs.master = _spawn_master(port, store, log)
        procs.worker = _spawn_worker(args, port, workdir, out, base_plan,
                                     log)
        remaining = list(chaos_events)
        while True:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"{tag}: soak did not converge within {args.timeout}s "
                    f"(events run: {events})")
            rc = procs.worker.poll()
            if rc == 0 and os.path.exists(os.path.join(workdir, "DONE")):
                break
            if rc is not None:
                # the worker died (SIGKILL'd, plan-killed, or crashed):
                # restart it — auto-resume, zero manual intervention.
                # A fresh incarnation gets a clean plan (a plan_kill
                # must fire once, not once per life).
                events.append(f"worker_exit:{rc}")
                procs.worker = _spawn_worker(args, port, workdir, out,
                                             base_plan, log)
            if procs.master.poll() is not None:
                events.append("master_exit")
                procs.master = _spawn_master(port, store, log)
            if remaining:
                time.sleep(schedule.uniform(0.5, 1.5))
                action = remaining.pop(0)
                events.append(action)
                if action == "kill_worker":
                    if procs.worker.poll() is None:
                        procs.worker.send_signal(signal.SIGKILL)
                elif action == "kill_master":
                    if procs.master.poll() is None:
                        procs.master.send_signal(signal.SIGKILL)
                elif action == "corrupt":
                    ckdir = os.path.join(workdir, "ckpt")
                    if os.path.isdir(ckdir):
                        npzs = sorted(n for n in os.listdir(ckdir)
                                      if n.endswith(".npz"))
                        if npzs:
                            victim = os.path.join(ckdir, npzs[-1])
                            try:
                                size = os.path.getsize(victim)
                                with open(victim, "r+b") as f:
                                    f.truncate(max(1, size // 2))
                            except OSError:
                                pass
                elif action == "plan_kill":
                    # deterministic in-process death: restart the worker
                    # with a plan killing it N steps into its life
                    if procs.worker.poll() is None:
                        procs.worker.kill()
                        procs.worker.wait()
                    k = schedule.randint(1, max(2, args.batches))
                    plan = json.dumps({"seed": args.seed, "faults": [
                        {"type": "kill", "site": "step_done", "at": k,
                         "mode": "exit"}]})
                    procs.worker = _spawn_worker(args, port, workdir, out,
                                                 plan, log)
            else:
                time.sleep(0.25)
        return out, events
    finally:
        procs.kill_all()
        log.close()


def controller_main(args) -> int:
    import numpy as np

    os.makedirs(args.workdir, exist_ok=True)
    log_path = os.path.join(args.workdir, "soak.log")
    t0 = time.time()
    clean_out, _ = _run_to_completion(args, "clean", [], log_path)

    rng = random.Random(args.seed)
    actions = ["kill_worker", "kill_master", "corrupt", "plan_kill"]
    # every action class appears; order seeded
    chaos_events = list(actions)
    while len(chaos_events) < args.events:
        chaos_events.append(rng.choice(actions))
    rng.shuffle(chaos_events)
    chaos_events = chaos_events[:max(args.events, 1)]

    chaos_out, events = _run_to_completion(args, "chaos", chaos_events,
                                           log_path)

    clean = np.load(clean_out)
    chaotic = np.load(chaos_out)
    mismatches = []
    if sorted(clean.files) != sorted(chaotic.files):
        mismatches.append("param-set differs")
    else:
        for k in clean.files:
            if not np.array_equal(clean[k], chaotic[k]):
                mismatches.append(k)
    result = {
        "soak": "chaos",
        "seed": args.seed,
        "passes": args.passes,
        "batches": args.batches,
        "events": events,
        "bitwise_equal": not mismatches,
        "mismatches": mismatches,
        "wall_s": round(time.time() - t0, 1),
    }
    print(json.dumps(result), flush=True)
    return 0 if not mismatches else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--role", choices=["controller", "worker"],
                    default="controller")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--passes", type=int, default=3)
    ap.add_argument("--batches", type=int, default=6)
    ap.add_argument("--events", type=int, default=4,
                    help="chaos actions in the seeded schedule")
    ap.add_argument("--timeout", type=float, default=900.0,
                    help="per-run convergence budget (seconds)")
    ap.add_argument("--workdir", default="/tmp/paddle_tpu_chaos_soak")
    ap.add_argument("--master", default="",
                    help="(worker) master host:port")
    ap.add_argument("--out", default="",
                    help="(worker) final-params npz path")
    args = ap.parse_args(argv)
    if args.role == "worker":
        return worker_main(args)
    return controller_main(args)


if __name__ == "__main__":
    sys.exit(main())
