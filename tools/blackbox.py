#!/usr/bin/env python
"""Merge per-process flight-recorder dumps into one fleet timeline.

    python tools/blackbox.py /path/to/flight_dir [--json] [--event E]

Every process in a fleet run dumps its own
``flight-<service>-<pid>.jsonl`` ring (``paddle_tpu/obs/flight.py``,
armed via ``$PADDLE_TPU_FLIGHT_DIR``) on SIGTERM / worker-fatal /
atexit — and BEFORE an ``os._exit`` chaos kill, which is the whole
point: the black box survives the crash it describes. This tool merges
those dumps by wall-clock ``ts`` (tie-broken by (pid, seq) so one
process's events keep their own order) and prints a readable timeline,
so a chaos postmortem — "lease expiry → adoption → first standby
answer" — is read off the artifact instead of re-run from the seed.

Importable: ``merge_dir(path)`` returns the ordered event list (the
soaks assert takeover sequences against it).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import List, Optional


def load_dump(path: str) -> List[dict]:
    events: List[dict] = []
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                # a torn tail line (the process died mid-write) is
                # expected in a black box; keep what parses
                sys.stderr.write(f"{path}:{i}: torn record skipped\n")
                continue
            if isinstance(rec, dict) and "event" in rec:
                events.append(rec)
    return events


def load_postmortem(path: str) -> Optional[dict]:
    """One divergence postmortem bundle (``obs/health.py:
    write_postmortem``) summarized as a timeline event: the bundle's
    own wall-clock ts/pid keep it ordered among the flight events of
    the trainer that dumped it; the full bundle stays on disk, the
    merged line carries the pointer."""
    try:
        with open(path, encoding="utf-8") as f:
            bundle = json.load(f)
    except (OSError, ValueError):
        sys.stderr.write(f"{path}: torn postmortem skipped\n")
        return None
    if not isinstance(bundle, dict):
        return None
    ev = {"ts": bundle.get("ts", 0.0), "pid": bundle.get("pid", 0),
          "seq": 0, "service": bundle.get("service", "train"),
          "event": "train.divergence.postmortem",
          "bundle": os.path.basename(path)}
    for k in ("step", "pass_id", "batch_id", "loss", "grad_absmax",
              "worst_layer", "policy"):
        if bundle.get(k) is not None:
            ev[k] = bundle[k]
    return ev


def merge_dir(path: str, pattern: str = "flight-*.jsonl",
              postmortems: Optional[str] = "postmortem-*.json"
              ) -> List[dict]:
    """All dumps under ``path`` — flight rings matching ``pattern``
    AND divergence postmortem bundles matching ``postmortems`` (its
    own glob so a ring-scoped ``pattern`` keeps its filtering
    contract; pass ``postmortems=None`` to exclude bundles) — merged
    into one wall-clock-ordered list. Sort key (ts, pid, seq): wall
    clock across processes, per-process seq within one (two
    processes' clocks may skew — the per-record ``mono`` field is
    there for forensic ordering within a process when they do)."""
    events: List[dict] = []
    for f in sorted(glob.glob(os.path.join(path, pattern))):
        events.extend(load_dump(f))
    for f in (sorted(glob.glob(os.path.join(path, postmortems)))
              if postmortems else ()):
        ev = load_postmortem(f)
        if ev is not None:
            events.append(ev)
    events.sort(key=lambda e: (e.get("ts", 0.0), e.get("pid", 0),
                               e.get("seq", 0)))
    return events


_CORE = ("ts", "mono", "seq", "service", "pid", "event")


def format_timeline(events: List[dict]) -> str:
    if not events:
        return "(no flight events)"
    t0 = events[0].get("ts", 0.0)
    lines = []
    for e in events:
        extra = " ".join(f"{k}={e[k]}" for k in e if k not in _CORE)
        lines.append(
            f"+{e.get('ts', 0.0) - t0:9.3f}s "
            f"[{e.get('service', '?')}/{e.get('pid', '?')}] "
            f"{e['event']}" + (f" {extra}" if extra else ""))
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="python tools/blackbox.py")
    ap.add_argument("dir", help="directory of flight-*.jsonl dumps "
                               "($PADDLE_TPU_FLIGHT_DIR)")
    ap.add_argument("--json", action="store_true",
                    help="emit the merged event list as JSON")
    ap.add_argument("--event", default=None,
                    help="filter to one event name")
    args = ap.parse_args(argv)
    events = merge_dir(args.dir)
    if args.event:
        events = [e for e in events if e["event"] == args.event]
    if args.json:
        print(json.dumps(events, indent=1))
    else:
        print(format_timeline(events))
    return 0 if events else 1


if __name__ == "__main__":
    sys.exit(main())
