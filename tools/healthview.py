#!/usr/bin/env python
"""Render / diff training-health timelines.

    python tools/healthview.py RUN.jsonl              # render one run
    python tools/healthview.py HEALTH_r16.json        # or an artifact
    python tools/healthview.py A.jsonl --diff B.jsonl # compare runs
    python tools/healthview.py RUN.jsonl --json       # normalized dump

Accepts both timeline shapes the health plane produces:

- the live per-run JSONL an ``obs/events.py:EventLog`` appends
  (``--health_log`` / ``HealthConfig.log_path``): one record per line,
  torn tail lines tolerated;
- the committed ``HEALTH_*.json`` artifact family (PT401): one object
  ``{"run", "period", "events": [...]}`` as ``bench.py --health``
  writes.

Rendering shows one line per step — loss, lr, max|grad|, the
data_wait/compute split — with ``!! divergence`` markers on sentry
trips (policy + postmortem pointer). ``--diff`` aligns two runs by
step and reports the first step whose loss differs (and the worst
absolute delta), which is how a telemetry-on vs telemetry-off pair or
a resumed-vs-uninterrupted pair is audited by eye.

Importable: ``load(path)`` -> (meta, step-events); ``diff(a, b)`` ->
{first_diverging_step, max_abs_delta, compared}.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
from typing import Dict, List, Optional, Tuple


def load(path: str) -> Tuple[dict, List[dict]]:
    """(meta, events) from a JSONL timeline or a HEALTH_* artifact.
    Events keep file order; non-step records (divergence markers) ride
    along tagged by their ``event`` field."""
    base = os.path.basename(path)
    if base.endswith(".jsonl"):
        from paddle_tpu.obs.events import load_timeline
        events = load_timeline(path)
        meta = {"run": base, "format": "jsonl"}
        return meta, events
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or not isinstance(
            data.get("events"), list):
        raise SystemExit(
            f"{path}: not a health timeline (expected a JSONL event "
            "log or a HEALTH_* artifact with an 'events' list)")
    meta = {k: v for k, v in data.items() if k != "events"}
    meta.setdefault("run", base)
    meta["format"] = "artifact"
    return meta, list(data["events"])


def steps_of(events: List[dict]) -> Dict[int, dict]:
    """step -> record for the per-step rows (event == "step" or
    untagged rows that carry a step+loss)."""
    out: Dict[int, dict] = {}
    for e in events:
        if not isinstance(e, dict):
            continue
        if e.get("event", "step") != "step":
            continue
        step = e.get("step")
        if isinstance(step, int):
            out[step] = e
    return out


def _fmt(v, width=10, prec=5) -> str:
    if v is None:
        return " " * (width - 1) + "-"
    if isinstance(v, str):  # non-finite floats serialize as strings
        return f"{v:>{width}}"
    if isinstance(v, float) and not math.isfinite(v):
        return f"{v!r:>{width}}"
    return f"{v:>{width}.{prec}g}"


def _stat(v) -> str:
    """One per-layer stat value — non-finite floats arrive as strings
    ("nan"/"inf", the EventLog strict-JSON spelling); print those raw
    (a divergence timeline is exactly where this tool must not
    crash)."""
    return v if isinstance(v, str) else f"{v:.5g}"


def _loss_of(rec) -> Optional[float]:
    """The record's loss as a float — EventLog spells non-finite
    losses as strings ("nan"/"inf") to keep lines strict JSON."""
    v = rec.get("loss")
    if isinstance(v, str):
        try:
            return float(v)
        except ValueError:
            return None
    if isinstance(v, (int, float)):
        return float(v)
    return None


def format_run(meta: dict, events: List[dict],
               layers: bool = False) -> str:
    lines = [f"run={meta.get('run')} period={meta.get('period', '?')} "
             f"events={len(events)}"]
    lines.append(f"{'step':>6} {'pass':>4} {'batch':>5} {'loss':>10} "
                 f"{'lr':>10} {'max|grad|':>10} {'wait_ms':>8} "
                 f"{'comp_ms':>8}")
    for e in events:
        kind = e.get("event", "step")
        if kind == "divergence":
            lines.append(
                f"!! divergence at step {e.get('step')}: "
                f"loss={e.get('loss')!r} "
                f"max|grad|={e.get('grad_absmax')!r} "
                f"worst={e.get('worst_layer')} "
                f"policy={e.get('policy')} "
                f"postmortem={e.get('postmortem')}")
            continue
        if kind != "step":
            continue
        mark = " *skipped" if e.get("skipped") else ""
        lines.append(
            f"{e.get('step', -1):>6} {e.get('pass', 0):>4} "
            f"{e.get('batch', 0):>5} {_fmt(e.get('loss'))} "
            f"{_fmt(e.get('lr'))} {_fmt(e.get('grad_absmax'))} "
            f"{_fmt(e.get('data_wait_ms'), 8, 3)} "
            f"{_fmt(e.get('compute_ms'), 8, 3)}{mark}")
        if layers and e.get("param_stats"):
            for n, d in sorted(e["param_stats"].items()):
                detail = " ".join(f"{k}={_stat(v)}"
                                  for k, v in sorted(d.items()))
                lines.append(f"       param {n}: {detail}")
        if layers and e.get("act_stats"):
            for n, d in sorted(e["act_stats"].items()):
                detail = " ".join(f"{k}={_stat(v)}"
                                  for k, v in sorted(d.items()))
                lines.append(f"       layer {n}: {detail}")
    return "\n".join(lines)


def diff(a_events: List[dict], b_events: List[dict]) -> dict:
    """Align two runs by step; report where their losses part ways.
    NaN != NaN would flag every post-divergence step, so two NaNs
    count as equal — the FIRST diverging step is the signal."""
    a, b = steps_of(a_events), steps_of(b_events)
    common = sorted(set(a) & set(b))
    first = None
    worst = 0.0
    for s in common:
        la, lb = _loss_of(a[s]), _loss_of(b[s])
        if la is None or lb is None:
            continue
        if math.isnan(la) and math.isnan(lb):
            continue
        delta = abs(la - lb)
        if delta > 0 and first is None:
            first = s
        if math.isfinite(delta):
            worst = max(worst, delta)
        elif first is None:
            first = s
    return {"compared": len(common),
            "only_a": len(set(a) - set(b)),
            "only_b": len(set(b) - set(a)),
            "first_diverging_step": first,
            "max_abs_delta": worst}


def format_diff(meta_a: dict, meta_b: dict, d: dict) -> str:
    lines = [f"A: {meta_a.get('run')}  B: {meta_b.get('run')}",
             f"steps compared: {d['compared']} "
             f"(only-A: {d['only_a']}, only-B: {d['only_b']})"]
    if d["first_diverging_step"] is None:
        lines.append("losses identical on every common step")
    else:
        lines.append(
            f"first diverging step: {d['first_diverging_step']} "
            f"(max |delta-loss| = {d['max_abs_delta']:.6g})")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="python tools/healthview.py")
    ap.add_argument("timeline", help="JSONL event log or HEALTH_*.json")
    ap.add_argument("--diff", default=None, metavar="OTHER",
                    help="second timeline to align by step")
    ap.add_argument("--layers", action="store_true",
                    help="expand per-layer stats on period steps")
    ap.add_argument("--json", action="store_true",
                    help="emit the normalized events (or diff) as JSON")
    args = ap.parse_args(argv)
    meta, events = load(args.timeline)
    if args.diff:
        meta_b, events_b = load(args.diff)
        d = diff(events, events_b)
        print(json.dumps(d, indent=1) if args.json
              else format_diff(meta, meta_b, d))
        return 0 if d["first_diverging_step"] is None else 1
    if args.json:
        print(json.dumps({"meta": meta, "events": events}, indent=1))
    else:
        print(format_run(meta, events, layers=args.layers))
    return 0 if events else 1


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    sys.exit(main())
