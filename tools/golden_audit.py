"""Full-field golden parity audit loop.

For every reference golden protostr, compare our exported LayerConfig /
ParameterConfig messages field-for-field (text format) against the
golden, after applying the documented normalizations, and print the
FIRST divergence per config. Drive this until the only output is
'all match', then lock the result in tests/test_compat_config.py::
test_golden_protostr_full_field_parity.

Usage: python tools/golden_audit.py [config.py ...]
"""

from __future__ import annotations

import pathlib
import sys

sys.path.insert(0, ".")

from google.protobuf import text_format  # noqa: E402

from paddle_tpu.compat import parse_config  # noqa: E402
from paddle_tpu.proto import ModelConfig_pb2, TrainerConfig_pb2  # noqa: E402

REF = pathlib.Path("/root/reference")
CFG_DIR = REF / "python/paddle/trainer_config_helpers/tests/configs"
GOLDEN_DIR = CFG_DIR / "protostr"


def golden_model(name):
    txt = (GOLDEN_DIR / (name[:-3] + ".protostr")).read_text()
    mc = ModelConfig_pb2.ModelConfig()
    try:
        text_format.Parse(txt, mc)
        return mc
    except text_format.ParseError:
        tc = TrainerConfig_pb2.TrainerConfig()
        text_format.Parse(txt, tc)
        return tc.model_config


def normalize_pair(ol, rl):
    """Documented divergences — see test_compat_config.py whitelist."""
    from tests.test_compat_config import normalize_layer_pair
    normalize_layer_pair(ol, rl)


def audit(name, verbose=False):
    parsed = parse_config(str(CFG_DIR / name))
    mine = parsed.model_proto()
    ref = golden_model(name)
    if [l.name for l in mine.layers] != [l.name for l in ref.layers]:
        return f"layer name lists differ"
    for ol, rl in zip(mine.layers, ref.layers):
        normalize_pair(ol, rl)
        a = text_format.MessageToString(ol)
        b = text_format.MessageToString(rl)
        if a != b:
            av, bv = a.splitlines(), b.splitlines()
            diff = [f"  ours: {x}\n  gold: {y}"
                    for x, y in zip(av, bv) if x != y]
            extra = ""
            if len(av) != len(bv):
                sa, sb = set(av), set(bv)
                extra = (f"\n  only-ours: {sorted(sa - sb)[:6]}"
                         f"\n  only-gold: {sorted(sb - sa)[:6]}")
            return (f"layer {ol.name!r} ({ol.type}):\n"
                    + "\n".join(diff[:4]) + extra)
    ours_p = {p.name: p for p in mine.parameters}
    ref_p = {p.name: p for p in ref.parameters}
    if set(ours_p) != set(ref_p):
        return f"param name sets differ: {set(ours_p) ^ set(ref_p)}"
    for pname in ours_p:
        from tests.test_compat_config import normalize_param_pair
        a, b = ours_p[pname], ref_p[pname]
        if a.size != b.size:
            return f"param {pname!r} size: {a.size} vs {b.size}"
        normalize_param_pair(a, b)
        ta = text_format.MessageToString(a)
        tb = text_format.MessageToString(b)
        if ta != tb:
            return (f"param {pname!r}:\n  ours: {ta!r}\n  gold: {tb!r}")
    return None


def main():
    names = sys.argv[1:]
    if not names:
        from tests.test_compat_config import GOLDEN_PARITY_CONFIGS
        names = GOLDEN_PARITY_CONFIGS
    bad = 0
    for name in names:
        try:
            msg = audit(name)
        except Exception as e:  # noqa: BLE001
            msg = f"EXCEPTION {e!r}"
        if msg:
            bad += 1
            print(f"== {name}: {msg}\n")
    print(f"{len(names) - bad}/{len(names)} match")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
