#!/bin/bash
# TPU tunnel watcher: probe gently on a loop; the moment the tunnel is
# live, capture the round's benchmark + kernel-evidence artifacts.
#
# The axon tunnel alternates between working windows and multi-hour
# wedges; two rounds produced zero TPU numbers by waiting for "later".
# This script makes capture automatic: run it in the background, check
# tpu_watch.log / the artifact files.
#
# PROBE DISCIPLINE (round-4 lesson): killing a probe mid-operation can
# WORSEN the wedge — the tunnel was live at round start and wedged right
# after a 90s-timeout matmul probe was SIGTERM-killed mid-compile (first
# compile over the tunnel can exceed 90s). So the probe is devices-only
# (no compile), the deadline is generous (240s), and failed probes back
# off 20 minutes so kills are rare.
#
# The bench child carries per-round extras (bench.py:child_main) — a
# capture window records them all for free: input_pipeline, zero1,
# pipeline, serving, decode, (r13) fleet — the AOT cold-start A/B,
# which on a real chip measures the tunnel's multi-minute XLA compiles
# against a millisecond cache deserialize — (r19) quant: the
# fp32/bf16/int8 serving three-way with the warmup accuracy gate
# asserted in-bench — and (r20) serve_train: the closed online loop
# (fleet under open-loop load, replay-tailed training, rolling
# publishes) with the error trajectory and zero-recompile guards
# asserted in-bench — and (r21) autotune: the defaults-vs-tuned A/B
# over the committed WORKLOAD_r21_* traces (record -> grid-tune ->
# replay-score), with replay determinism, tuned-beats-defaults and
# zero non-shed failures asserted in-bench.
#
# Usage: bash tools/tpu_watch.sh [round_tag]   (default r04)
set -u
cd "$(dirname "$0")/.."
TAG="${1:-r04}"
LOG=tpu_watch.log
echo "[$(date -u +%H:%M:%S)] watcher start (gentle probe)" >>"$LOG"
while true; do
  if timeout -k 15 240 python -c "import jax; print(jax.devices()[0].platform)" >>"$LOG" 2>&1; then
    echo "[$(date -u +%H:%M:%S)] TUNNEL LIVE — capturing" >>"$LOG"
    ok=1
    # bench first (the headline artifact), evidence second; a capture
    # that fails mid-wedge must NOT end the watch — re-enter the probe
    # loop so a later working window still produces the artifacts
    if BENCH_RETRIES=1 timeout 4500 python bench.py >"BENCH_LIVE_${TAG}.json.tmp" 2>>"$LOG" \
        && grep -q '"value":' "BENCH_LIVE_${TAG}.json.tmp"; then
      mv "BENCH_LIVE_${TAG}.json.tmp" "BENCH_LIVE_${TAG}.json"
      echo "[$(date -u +%H:%M:%S)] bench captured" >>"$LOG"
    else
      echo "[$(date -u +%H:%M:%S)] bench FAILED" >>"$LOG"; ok=0
    fi
    if timeout 2400 python tools/tpu_evidence.py >>"$LOG" 2>&1; then
      echo "[$(date -u +%H:%M:%S)] evidence captured" >>"$LOG"
    else
      echo "[$(date -u +%H:%M:%S)] evidence FAILED rc=$?" >>"$LOG"; ok=0
    fi
    if [ "$ok" = 1 ]; then
      echo "[$(date -u +%H:%M:%S)] capture pass done" >>"$LOG"
      exit 0
    fi
    echo "[$(date -u +%H:%M:%S)] capture incomplete; re-entering probe loop" >>"$LOG"
  fi
  echo "[$(date -u +%H:%M:%S)] tunnel wedged/incomplete; retry in 1200s" >>"$LOG"
  sleep 1200
done
