"""Sparse-updater CTR accuracy evidence (ACCURACY_r08.json).

VERDICT r05 Missing #4: the r05 CTR entry trained `trainer_config.lr.py`
DENSELY on a 209-sentence CoNLL proxy; BASELINE config #5 is "quick_start
CTR ... with sparse updater". This run replaces that proxy entry:

- **model**: `models/ctr.py:ctr_model` — the quick_start CTR family
  (word-id sequence -> embedding -> average pooling -> fc -> binary
  classification) with the embedding flagged ``sparse_grad=True`` (the
  reference's ``sparse_update`` ParamAttr, `SparseRowMatrix.h:204`,
  `RemoteParameterUpdater.h:265`), selecting the lazy touched-rows-only
  optimizer path end to end;
- **corpus**: REAL Amazon product reviews — the quick_start demo's
  actual dataset family (its fetch script downloads Amazon review
  polarity; this host has the McAuley 2014 dump checked in at
  /root/datasets/amazon_reviews). Musical Instruments 5-core split,
  binary sentiment (overall>=4 positive, <=2 negative, 3s dropped — the
  demo's polarity convention), with a held-out test split;
- **metric**: held-out classification error per pass.

The multichip dryrun (`__graft_entry__.py`) runs the SAME config
row-sharded over the model axis ("sparse CTR step OK, table row-sharded
N-way" in MULTICHIP_r08.json) — together: accuracy on real data through
the sparse path + sharded execution of the identical model.
"""

from __future__ import annotations

import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

CORPUS = ("/root/datasets/amazon_reviews/untarred/data_dir/5core/"
          "reviews_Amazon_Instant_Video_5.json")
OUT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "ACCURACY_r08.json")

VOCAB = 5000
MAX_LEN = 64
N_TRAIN, N_TEST = 6000, 1500
BATCH = 100
PASSES = int(os.environ.get("CTR_PASSES", "25"))


def load_corpus():
    """(texts, labels) — balanced-ish binary sentiment from the 5-core
    reviews; deterministic order."""
    import numpy as np
    texts, labels = [], []
    with open(CORPUS) as f:
        for line in f:
            r = json.loads(line)
            overall = r.get("overall", 3.0)
            if overall == 3.0:
                continue  # the demo's polarity convention drops neutral
            texts.append(r.get("reviewText", "") or "")
            labels.append(1 if overall >= 4.0 else 0)
            if len(texts) >= 4 * (N_TRAIN + N_TEST):
                break
    order = np.random.RandomState(0).permutation(len(texts))
    # 5-core reviews skew positive ~85/15: subsample positives so the
    # error metric cannot be gamed by the majority class
    neg = [i for i in order if labels[i] == 0]
    pos = [i for i in order if labels[i] == 1][:2 * len(neg)]
    keep = list(np.random.RandomState(1).permutation(neg + pos))
    keep = keep[:N_TRAIN + N_TEST]
    return [texts[i] for i in keep], [labels[i] for i in keep]


def tokenize(text):
    return re.findall(r"[a-z']+", text.lower())[:MAX_LEN]


def build_dict(texts):
    from collections import Counter
    c = Counter(w for t in texts for w in tokenize(t))
    # id 0..VOCAB-1; OOV words drop (DataFeeder validates ids)
    return {w: i for i, (w, _) in enumerate(c.most_common(VOCAB))}


def main():
    t0 = time.time()
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from paddle_tpu.config import dsl
    from paddle_tpu.data import (DataFeeder, integer_value,
                                 integer_value_sequence)
    from paddle_tpu.models import ctr_model
    from paddle_tpu.optim import Momentum
    from paddle_tpu.trainer import SGD

    texts, labels = load_corpus()
    # the balanced subset may be smaller than the nominal split (5-core
    # reviews skew heavily positive): hold out 1/5, cap at N_TEST
    n_test = min(N_TEST, len(texts) // 5)
    n_train = len(texts) - n_test
    vocab = build_dict(texts[:n_train])
    n_neg = labels[:n_train].count(0)

    def encode(t):
        ids = [vocab[w] for w in tokenize(t) if w in vocab]
        return ids or [0]

    train = [(encode(t), l) for t, l in zip(texts[:n_train],
                                            labels[:n_train])]
    test = [(encode(t), l) for t, l in zip(texts[n_train:],
                                           labels[n_train:])]

    dsl.reset()
    cost, out, _ = ctr_model(vocab_size=VOCAB, embed_dim=32, hidden=64,
                             classes=2)
    # Momentum: the optimizer family whose sparse_update has the lazy
    # touched-rows path (the reference's SparseMomentumParameterOptimizer,
    # FirstOrderOptimizer.h:64-122) — the point of this run
    trainer = SGD(cost=cost,
                  update_equation=Momentum(learning_rate=0.05,
                                           momentum=0.9),
                  seed=0)
    spec = trainer.meta["_embed.w0"]
    assert spec.sparse_grad, "embedding lost its sparse_update flag"
    assert "t_rows" in trainer.opt_state["slots"]["_embed.w0"], \
        "sparse table did not take the lazy touched-rows path"

    feeder = DataFeeder({"words": integer_value_sequence(VOCAB),
                         "label": integer_value(2)}, pad_multiple=MAX_LEN)

    def reader(data):
        def r():
            for i in range(0, len(data) - BATCH + 1, BATCH):
                yield data[i:i + BATCH]
        return r

    history = []
    for p in range(PASSES):
        trainer.train(reader(train), feeder=feeder, num_passes=1)
        res = trainer.test(reader(test), feeder=feeder)
        err = res.evaluator.get("classification_error")
        history.append(round(float(err), 5))
        print(f"pass {p}: heldout_error={err:.4f}", flush=True)

    entry = {
        "config": "models/ctr.py:ctr_model (the quick_start CTR family: "
                  "embedding(sparse_update=True) -> avg pooling -> fc -> "
                  "binary classification; lazy touched-rows optimizer "
                  "path asserted on _embed.w0)",
        "corpus": "REAL Amazon product reviews (McAuley 2014, Instant "
                  "Video 5-core) — the quick_start demo's actual "
                  "dataset family; binary sentiment (>=4 pos, <=2 neg, "
                  "3s dropped), positives subsampled 2:1",
        "sparse_update": True,
        "rc": 0,
        "passes": PASSES,
        "vocab": VOCAB,
        "train_samples": len(train),
        "heldout_samples": len(test),
        "train_neg_fraction": round(n_neg / max(n_train, 1), 3),
        "heldout_error_by_pass": history,
        "final_heldout_error": history[-1],
        "best_heldout_error": min(history),
        "majority_class_error": round(
            min(n_neg, n_train - n_neg) / max(n_train, 1), 3),
        "dryrun_row_sharded": "MULTICHIP_r08.json: 'sparse CTR step OK, "
                              "table row-sharded 4-way' runs the same "
                              "ctr_model over the (data, model) mesh",
        "wall_s": round(time.time() - t0, 1),
    }
    doc = {
        "platform": "cpu",
        "note": "r08 replaces the r05 dense CoNLL-proxy CTR entry "
                "(VERDICT Missing #4): the sparse updater now trains the "
                "quick_start CTR shape on its real corpus family with a "
                "held-out metric. Other r05 entries (MNIST, rnn_crf, "
                "seq2seq) are unchanged and live in ACCURACY_r05.json.",
        "quick_start_ctr_sparse": entry,
    }
    with open(OUT, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps(entry)[:400], flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
