"""Live-TPU kernel evidence: compiled Pallas vs reference, on-device checkgrad.

Runs only when a real TPU backend is present. Produces `TPU_EVIDENCE.json`
at the repo root with, per kernel (fused LSTM / fused GRU / flash
attention):

- forward + backward numerical parity between the *compiled* Pallas kernel
  (``force_mode("pallas")``) and the pure-JAX reference implementation
  (``force_mode("ref")``) — the reference's CPU-stub-vs-GPU-kernel
  equivalence tests (`paddle/math/tests/test_matrixCompare.cpp`) at TPU
  granularity;
- steady-state per-call timing for both paths (compiled Pallas must not be
  slower than the XLA reference to be worth shipping);
- a numeric-vs-analytic directional-derivative check of the hand-written
  VJPs executed **on the TPU** (`Trainer::checkGradient`,
  `paddle/trainer/Trainer.cpp:299`, on device numerics).

Usage: ``python tools/tpu_evidence.py`` (writes TPU_EVIDENCE.json, prints it).
"""

from __future__ import annotations

import json
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from paddle_tpu.ops import common  # noqa: E402
from paddle_tpu.ops.attention import flash_attention  # noqa: E402
from paddle_tpu.ops.gru import gru_sequence  # noqa: E402
from paddle_tpu.ops.lstm import lstm_sequence  # noqa: E402


def _timeit(fn, *args):
    """Per-call seconds with the tunnel round-trip cancelled.

    bench.py's chain trick: dispatch N dependent steps (the first input is
    perturbed by the previous step's output so every dispatch is a fresh
    computation the runtime cannot serve from cache), fetch ONE scalar to
    close the window, and take the difference quotient of a long and a
    short chain — the constant round-trip latency cancels."""
    x0, rest = args[0], args[1:]

    @jax.jit
    def step(x):
        out = fn(x, *rest)
        out0 = out[0] if isinstance(out, tuple) else out
        return x + jnp.sum(out0) * 1e-30

    def chain(n):
        x = x0
        t0 = time.perf_counter()
        for _ in range(n):
            x = step(x)
        float(jnp.sum(x) * 0 + x.reshape(-1)[0])  # one scalar fetch
        return time.perf_counter() - t0

    chain(2)  # compile + warm
    long_n, short_n = 60, 6
    t_long = min(chain(long_n) for _ in range(2))
    t_short = min(chain(short_n) for _ in range(2))
    return max(t_long - t_short, 1e-9) / (long_n - short_n)


def _compare(name, make_fn, args, grad_argnums, report):
    """Forward+grad parity (pallas vs ref) and timing for one kernel."""
    entry = {}

    def run(mode):
        # jax's trace cache is keyed on the function object, so without a
        # cache clear the second mode would silently reuse the first mode's
        # lowering and the comparison would compare the kernel to itself
        jax.clear_caches()
        with common.force_mode(mode):
            fwd = jax.jit(make_fn)
            loss = jax.jit(lambda *a: jnp.sum(
                (fwd(*a)[0] if isinstance(fwd(*a), tuple) else fwd(*a)) ** 2))
            grads = jax.jit(jax.grad(loss, argnums=grad_argnums))
            lowered = fwd.lower(*args).as_text()
            out = fwd(*args)
            out0 = out[0] if isinstance(out, tuple) else out
            g = grads(*args)
            # materialize before leaving the force_mode scope
            out0, g = jax.device_get((out0, g))
            t = _timeit(fwd, *args)
            return out0, g, t, "tpu_custom_call" in lowered

    out_p, g_p, t_p, cc_p = run("pallas")
    out_r, g_r, t_r, cc_r = run("ref")
    # the two modes must actually be different compiled programs
    assert cc_p and not cc_r, (name, cc_p, cc_r)
    fwd_err = float(np.max(np.abs(out_p - out_r)) /
                    (np.max(np.abs(out_r)) + 1e-8))
    grad_err = max(
        float(np.max(np.abs(a - b)) / (np.max(np.abs(b)) + 1e-8))
        for a, b in zip(g_p, g_r))
    entry["fwd_rel_err_vs_ref"] = round(fwd_err, 8)
    entry["grad_rel_err_vs_ref"] = round(grad_err, 8)
    # below ~20us/call the difference quotient is tunnel jitter, not kernel
    # time: report null rather than a fake number
    valid = t_p > 2e-5 and t_r > 2e-5
    entry["pallas_ms"] = round(t_p * 1e3, 3) if valid else None
    entry["ref_xla_ms"] = round(t_r * 1e3, 3) if valid else None
    entry["pallas_speedup_vs_ref"] = round(t_r / t_p, 3) if valid else None
    entry["parity_ok"] = bool(fwd_err < 2e-2 and grad_err < 5e-2)
    report[name] = entry
    print(f"{name}: fwd_err={fwd_err:.2e} grad_err={grad_err:.2e} "
          f"pallas={t_p * 1e3:.2f}ms ref={t_r * 1e3:.2f}ms", flush=True)


def _checkgrad(name, make_loss, args, report, eps=1e-3):
    """Directional numeric-vs-analytic derivative on the TPU, highest
    matmul precision (the --job=checkgrad contract on device numerics)."""
    with jax.default_matmul_precision("highest"):
        loss = jax.jit(make_loss)
        grads = jax.jit(jax.grad(make_loss, argnums=tuple(range(len(args)))))
        g = grads(*args)
        rng = np.random.RandomState(7)
        dirs = [jnp.asarray(rng.randn(*np.shape(a)).astype(np.float32))
                for a in args]
        analytic = float(sum(jnp.vdot(gi, di) for gi, di in zip(g, dirs)))
        plus = loss(*[a + eps * d for a, d in zip(args, dirs)])
        minus = loss(*[a - eps * d for a, d in zip(args, dirs)])
        numeric = float((plus - minus) / (2 * eps))
    rel = abs(analytic - numeric) / (abs(numeric) + 1e-8)
    ok = rel < 5e-2
    report.setdefault("checkgrad", {})[name] = {
        "analytic": analytic, "numeric": numeric,
        "rel_err": round(rel, 8), "ok": bool(ok)}
    print(f"checkgrad[{name}]: analytic={analytic:.6f} numeric={numeric:.6f} "
          f"rel={rel:.2e}", flush=True)


def main():
    backend = jax.default_backend()
    dev = jax.devices()[0]
    report = {
        "backend": backend,
        "device_kind": dev.device_kind,
        "note": "compiled Pallas kernels vs pure-JAX reference, on real TPU",
    }
    if backend != "tpu":
        report["error"] = f"no TPU backend (got {backend}); evidence not run"
        print(json.dumps(report))
        return 1

    rng = np.random.RandomState(0)

    def arr(*shape, scale=0.2):
        return jnp.asarray(rng.randn(*shape).astype(np.float32) * scale)

    # ---- fused LSTM (bench shape: T=100, B=64, H=256)
    # The gate bias is pre-folded into xs for BOTH paths: the Pallas entry
    # folds it before the kernel while the scan reference adds it after the
    # recurrent matmul, and that single add-reorder (1 ulp at t=0) amplifies
    # chaotically through 100 recurrent steps (measured: 0.23 max abs by
    # t=98, bitwise 0.0 when folded identically). Parity must compare the
    # same rounding schedule, not the recurrence's Lyapunov exponent.
    T, B, H = 100, 64, 256
    mask = jnp.ones((T, B), jnp.float32)
    xs = arr(T, B, 4 * H) + arr(4 * H)  # input with bias pre-folded
    w, zbias = arr(H, 4 * H), jnp.zeros((4 * H,), jnp.float32)
    zc = jnp.zeros((H,), jnp.float32)
    h0 = c0 = jnp.zeros((B, H), jnp.float32)
    _compare(
        "lstm_sequence",
        lambda xs_, w_: lstm_sequence(xs_, mask, w_, zbias, zc, zc, zc,
                                      h0, c0),
        (xs, w), (0, 1), report)

    # ---- fused LSTM at the big-hidden BASELINE row (h=1280, bs=64:
    # benchmark/README.md:108-127) — takes the TILED kernel (the weight
    # no longer fits VMEM; lstm_dispatch must not fall back to scan)
    from paddle_tpu.ops.lstm import lstm_dispatch
    H2 = 1280
    with common.force_mode("pallas"):
        assert lstm_dispatch(B, H2) == "tiled", \
            lstm_dispatch(B, H2)
    mask2 = jnp.ones((T, B), jnp.float32)
    xs2 = arr(T, B, 4 * H2, scale=0.1) + arr(4 * H2, scale=0.1)
    w2 = arr(H2, 4 * H2, scale=0.05)
    zb2 = jnp.zeros((4 * H2,), jnp.float32)
    zc2 = jnp.zeros((H2,), jnp.float32)
    h02 = c02 = jnp.zeros((B, H2), jnp.float32)
    _compare(
        "lstm_sequence_h1280_tiled",
        lambda xs_, w_: lstm_sequence(xs_, mask2, w_, zb2, zc2, zc2, zc2,
                                      h02, c02),
        (xs2, w2), (0, 1), report)

    # ---- fused GRU
    xg, wg, ws = arr(T, B, 3 * H), arr(H, 2 * H), arr(H, H)
    bg = arr(3 * H)
    _compare(
        "gru_sequence",
        lambda xs_, wg_, ws_: gru_sequence(xs_, mask, wg_, ws_, bg, h0),
        (xg, wg, ws), (0, 1, 2), report)

    # ---- flash attention (B=4, heads=8, T=1024, D=64, causal)
    q, k, v = arr(4, 8, 1024, 64), arr(4, 8, 1024, 64), arr(4, 8, 1024, 64)
    _compare(
        "flash_attention",
        partial(flash_attention, causal=True),
        (q, k, v), (0, 1, 2), report)

    # ---- CRF partition function (exp-space MXU matmul DP; 9 classes
    # padded to the 128-lane width inside the dispatcher)
    from paddle_tpu.ops.crf import crf_log_z
    xc = arr(64, 32, 9, scale=1.0)
    maskc = jnp.ones((64, 32), jnp.float32)
    transc, ac, bc = arr(9, 9, scale=1.0), arr(9, scale=1.0), \
        arr(9, scale=1.0)
    _compare(
        "crf_log_z",
        lambda x_, t_: crf_log_z(x_, maskc, t_, ac, bc),
        (xc, transc), (0, 1), report)

    # ---- CTC (extended axis 2L+1=17 padded to 128 in the dispatcher)
    from paddle_tpu.layers.chain import ctc_loss
    lp = jax.nn.log_softmax(arr(32, 40, 12, scale=1.0), axis=-1)
    lab = jnp.asarray(rng.randint(0, 11, size=(32, 8)).astype(np.int32))
    in_m = jnp.ones((32, 40), jnp.float32)
    lab_m = jnp.ones((32, 8), jnp.float32)
    _compare(
        "ctc_loss",
        lambda lp_: ctc_loss(lp_, lab, in_m, lab_m, blank=11),
        (lp,), (0,), report)

    # ---- on-device checkgrad of the custom VJPs (small TPU-tiled shapes)
    t, b, h = 8, 8, 128
    cx, cm = arr(t, b, 4 * h), jnp.ones((t, b), jnp.float32)
    cw, cb = arr(h, 4 * h), arr(4 * h)
    czc = jnp.zeros((h,), jnp.float32)
    ch = cc = jnp.zeros((b, h), jnp.float32)
    with common.force_mode("pallas"):
        _checkgrad(
            "lstm_pallas",
            lambda xs_, w_: jnp.sum(lstm_sequence(
                xs_, cm, w_, cb, czc, czc, czc, ch, cc)[0] ** 2),
            (cx, cw), report)
        gx, gwg, gws, gb = arr(t, b, 3 * h), arr(h, 2 * h), arr(h, h), \
            arr(3 * h)
        _checkgrad(
            "gru_pallas",
            lambda xs_, wg_, ws_: jnp.sum(gru_sequence(
                xs_, cm, wg_, ws_, gb, ch)[0] ** 2),
            (gx, gwg, gws), report)
        fq, fk, fv = arr(2, 2, 256, 64), arr(2, 2, 256, 64), \
            arr(2, 2, 256, 64)
        _checkgrad(
            "flash_attention_pallas",
            lambda q_, k_, v_: jnp.sum(
                flash_attention(q_, k_, v_, causal=True) ** 2),
            (fq, fk, fv), report)
        kx = arr(8, 6, 9, scale=1.0)
        kmask = jnp.ones((8, 6), jnp.float32)
        ktr, ka, kb = arr(9, 9, scale=1.0), arr(9, scale=1.0), \
            arr(9, scale=1.0)
        _checkgrad(
            "crf_pallas",
            lambda x_, t_: jnp.sum(crf_log_z(x_, kmask, t_, ka, kb) ** 2),
            (kx, ktr), report)

    report["all_parity_ok"] = all(
        report[k]["parity_ok"]
        for k in ("lstm_sequence", "lstm_sequence_h1280_tiled",
                  "gru_sequence", "flash_attention",
                  "crf_log_z", "ctc_loss"))
    report["all_checkgrad_ok"] = all(
        v["ok"] for v in report["checkgrad"].values())
    with open("TPU_EVIDENCE.json", "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
