#!/usr/bin/env python
"""CI lint entry: graftlint's five passes + the artifact schema check,
with rule-count summary and non-zero exit on any finding.

    python tools/lint.py            # everything (jaxpr+shard+mem audits)
    python tools/lint.py --fast     # AST + locks + schema only
    python tools/lint.py --no-entry # audit without the ResNet build
    python tools/lint.py --json     # machine-readable findings (CI)
    python tools/lint.py --budgets  # current-vs-pinned budget tables
                                    # (read-only; comm + mem ratchets)

This is a thin wrapper over ``python -m paddle_tpu.analysis`` so CI
and humans run the identical engine; see docs/static_analysis.md for
the rule catalog and suppression policy.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    # CPU-platform forcing (wedged-tunnel protection) lives in ONE
    # place: paddle_tpu.analysis.__main__.run(), which this calls
    argv = sys.argv[1:]
    if "--fast" in argv:
        # passes 4/5 (sharding/collective + memory audits) are
        # full-mode only: they compile the parallel programs on the
        # virtual mesh, and --fast must stay under ~10s on the 1-core
        # host
        argv = [a for a in argv if a != "--fast"] + [
            "--skip-jaxpr", "--skip-shard", "--skip-mem"]
    from paddle_tpu.analysis.__main__ import run

    return run(argv)


if __name__ == "__main__":
    sys.exit(main())
