"""North-star accuracy evidence (ACCURACY_r05.json).

Trains reference configs UNMODIFIED through the CLI on the only real
MNIST corpus present in this offline environment: the reference's own
checked-in proto shard (``paddle/trainer/tests/mnist_bin_part``, 1227
genuine MNIST digits — the download scripts in ``v1_api_demo/mnist/data``
need network egress this machine does not have).

Jobs (MNIST ones on an 827/400 train/held-out split of the real shard, with
per-pass held-out evaluation; the user-side data provider module
(``mnist_provider`` — user code in the demo) is substituted with one
that reads the proto shard; the CONFIGS — network, optimizer, batch
size, regularization — run unmodified):
1. ``v1_api_demo/mnist/light_mnist.py`` (conv groups + Adam).
2. ``v1_api_demo/mnist/vgg_16_mnist.py`` (small_vgg + Momentum,
   the north-star demo config).

Honest caveat recorded in the artifact: 1227 samples is ~2% of MNIST;
reference-grade (99%+) test accuracy requires the full 60k corpus,
which cannot be downloaded here. The evidence shows the training
pipeline drives real data to high accuracy, not full-corpus parity.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import re
import sys
import time
import types

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REF_TESTS = "/root/reference/paddle/trainer/tests"
VGG_CONFIG = "/root/reference/v1_api_demo/mnist/vgg_16_mnist.py"


def split_shard(workdir: str, n_test: int = 400):
    """mnist_bin_part -> train/test shards with the demo's
    data/{train,test}.list layout. Held-out 400 of 1227 (round-4 weak
    #4: a 127-sample eval set could not tell LeNet from VGG)."""
    import numpy as np

    from paddle_tpu.data.protodata import read_messages, write_shard
    header, samples = read_messages(os.path.join(REF_TESTS,
                                                 "mnist_bin_part"))
    samples = list(samples)
    # the shard is label-sorted — a tail split would hold out a class
    # the training set barely contains; shuffle deterministically
    order = np.random.RandomState(0).permutation(len(samples))
    samples = [samples[i] for i in order]
    os.makedirs(os.path.join(workdir, "data"), exist_ok=True)
    train_p = os.path.join(workdir, "data", "train.shard")
    test_p = os.path.join(workdir, "data", "test.shard")
    write_shard(train_p, header, samples[:-n_test])
    write_shard(test_p, header, samples[-n_test:])
    with open(os.path.join(workdir, "data", "train.list"), "w") as f:
        f.write(train_p + "\n")
    with open(os.path.join(workdir, "data", "test.list"), "w") as f:
        f.write(test_p + "\n")
    return len(samples)


def install_provider_shim():
    """A ``mnist_provider`` module reading proto shards with the demo
    provider's exact interface (pixel scaled to [-1, 1] like
    ``mnist_util.read_from_mnist``)."""
    from paddle_tpu.compat import install_paddle_alias
    install_paddle_alias()
    from paddle.trainer.PyDataProvider2 import (dense_vector,  # noqa
                                                integer_value, provider)

    mod = types.ModuleType("mnist_provider")

    @provider(input_types={"pixel": dense_vector(28 * 28),
                           "label": integer_value(10)})
    def process(settings, filename):
        from paddle_tpu.data.protodata import ProtoDataReader
        for pixel, label in ProtoDataReader([filename])():
            yield {"pixel": pixel * 2.0 - 1.0, "label": int(label)}

    mod.process = process
    sys.modules["mnist_provider"] = mod
    return mod


CONLL_TRAIN = "/root/reference/paddle/trainer/tests/train.txt"
CONLL_TEST = "/root/reference/paddle/trainer/tests/test.txt"
TAG_PROVIDER = "/root/reference/v1_api_demo/sequence_tagging/dataprovider.py"


def setup_conll(workdir: str):
    """Stage the REAL checked-in CoNLL-2000 slice (``paddle/trainer/
    tests/train.txt``: 5000 lines / ``test.txt``: 1000 lines — the
    corpus the reference's own chunking.conf trains on) in the demo's
    expected layout (data/train.txt.gz + list files)."""
    import gzip
    import shutil
    d = os.path.join(workdir, "data")
    os.makedirs(d, exist_ok=True)
    for src, name in ((CONLL_TRAIN, "train.txt.gz"),
                      (CONLL_TEST, "test.txt.gz")):
        with open(src, "rb") as fin, gzip.open(
                os.path.join(d, name), "wb") as fout:
            shutil.copyfileobj(fin, fout)
    with open(os.path.join(d, "train.list"), "w") as f:
        f.write("data/train.txt.gz\n")
    with open(os.path.join(d, "test.list"), "w") as f:
        f.write("data/test.txt.gz\n")


def install_tagging_provider(workdir: str):
    """Write a ``dataprovider`` wrapper module into workdir that execs
    the demo's provider VERBATIM (featurization, dictionaries, IOB label
    map all the reference's own code) with three documented shims:

    1. python-2 compat: ``xrange`` + text-mode gzip (the file is py2).
    2. input_types dims overridden to the CONFIG's hardcoded full-corpus
       sizes (word 6778 / pos 44 / chunk 23 / features 76328): the
       5000-line slice builds smaller dicts, and ids stay in range.
    3. OOV policy word/pos -> USE (id 0): the reference's IGNORE policy
       emits the py2 engine's 0xffffffff skip sentinel, which is far
       more frequent on a 5000-line dict and has no engine meaning here.
    """
    with open(os.path.join(workdir, "dataprovider.py"), "w") as f:
        f.write(f'''\
import gzip as _gzip

_src = open({TAG_PROVIDER!r}).read()
# mechanical py2->py3 token translation (no logic change)
_src = _src.replace(".iteritems()", ".items()")
_src = _src.replace(".iterkeys()", ".keys()")
_src = _src.replace(".itervalues()", ".values()")
# py2 shim in the exec'd module's OWN globals (no builtins mutation)
_ns = {{"__name__": "ref_tagging_provider", "xrange": range}}
exec(compile(_src, {TAG_PROVIDER!r}, "exec"), _ns)


class _GzipText:
    """py2 gzip.open read str; py3 'rb' yields bytes and breaks
    line.split(' ') — reopen in text mode."""

    @staticmethod
    def open(filename, mode="rt"):
        return _gzip.open(filename, "rt")


_ns["gzip"] = _GzipText
_ref = _ns["process"]  # the demo's decorated DataProvider

from paddle.trainer.PyDataProvider2 import (CacheType, provider,
                                            integer_value_sequence,
                                            sparse_binary_vector_sequence)


def _init(settings, **xargs):
    _ref.init_hook(settings, **xargs)
    settings.oov_policy[0] = _ns["OOV_POLICY_USE"]
    settings.oov_policy[1] = _ns["OOV_POLICY_USE"]
    settings.input_types = [
        integer_value_sequence(6778),
        integer_value_sequence(44),
        integer_value_sequence(23),
        sparse_binary_vector_sequence(76328),
    ]


process = provider(init_hook=_init,
                   cache=CacheType.CACHE_PASS_IN_MEM)(_ref.generator)
''')


def job_sequence_tagging(workdir: str, passes: int):
    """rnn_crf.py (BiLSTM-CRF, the sequence-tagging north star) on the
    real CoNLL-2000 slice; held-out chunk-F1 + per-token error."""
    install_provider_shim()
    setup_conll(workdir)
    install_tagging_provider(workdir)
    # the config's own directory (holding the py2 provider) is prepended
    # to sys.path by the reader; pre-planting the wrapper in sys.modules
    # makes __import__("dataprovider") resolve to it
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "dataprovider", os.path.join(workdir, "dataprovider.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules["dataprovider"] = mod
    spec.loader.exec_module(mod)
    t0 = time.time()
    cwd = os.getcwd()
    os.chdir(workdir)
    sys.path.insert(0, workdir)
    try:
        rc, out = run_cli([
            "--config",
            "/root/reference/v1_api_demo/sequence_tagging/rnn_crf.py",
            "--job", "train", "--num_passes", str(passes),
            "--test_period", "1", "--log_period", "0"])
    finally:
        os.chdir(cwd)
        sys.path.remove(workdir)
    return {
        "config": "v1_api_demo/sequence_tagging/rnn_crf.py (unmodified; "
                  "demo dataprovider exec'd verbatim with documented "
                  "py2/dims/OOV shims)",
        "corpus": "REAL CoNLL-2000 slice checked into the reference "
                  "(paddle/trainer/tests/train.txt 5000 lines train, "
                  "test.txt 1000 lines held out — the corpus "
                  "chunking.conf ships with)",
        "rc": rc, "passes": passes,
        "final_train_chunk_f1": last_metric(out, r"Pass \d+:", "chunk_f1"),
        "heldout_chunk_f1": last_metric(out, r"Test:", "chunk_f1"),
        "heldout_error_sum": last_metric(out, r"Test:", "error"),
        "wall_s": round(time.time() - t0, 1),
    }


def _conll_sentences(path):
    cur = []
    for ln in open(path):
        ln = ln.strip()
        if not ln:
            if cur:
                yield cur
                cur = []
            continue
        cur.append(ln.split(" "))
    if cur:
        yield cur


def job_quick_start_ctr(workdir: str, passes: int):
    """quick_start trainer_config.lr.py (BOW logistic regression, the
    CTR north star) + dataprovider_bow.py, both UNMODIFIED, on a real
    derived task: the checked-in CoNLL-2000 sentences, label = sentence
    contains a past-tense verb (VBD). The demo's Amazon corpus needs
    egress; this keeps real English text + a real linguistic label
    (61%/56% positive in train/held-out)."""
    install_provider_shim()
    d = os.path.join(workdir, "data")
    os.makedirs(d, exist_ok=True)
    vocab = {}
    for split, src in (("train", CONLL_TRAIN), ("test", CONLL_TEST)):
        lines = []
        for sent in _conll_sentences(src):
            words = [w[0] for w in sent]
            label = int(any(w[1] == "VBD" for w in sent))
            lines.append(f"{label}\t{' '.join(words)}")
            if split == "train":
                for w in words:
                    vocab[w] = vocab.get(w, 0) + 1
        with open(os.path.join(d, f"{split}.txt"), "w") as f:
            f.write("\n".join(lines) + "\n")
        with open(os.path.join(d, f"{split}.list"), "w") as f:
            f.write(f"data/{split}.txt\n")
    with open(os.path.join(d, "dict.txt"), "w") as f:
        f.write("<unk>\t-1\n")  # UNK_IDX=0 in the provider
        for i, w in enumerate(sorted(vocab, key=lambda k: -vocab[k])):
            f.write(f"{w}\t{i}\n")
    t0 = time.time()
    cwd = os.getcwd()
    os.chdir(workdir)
    sys.path.insert(0, "/root/reference/v1_api_demo/quick_start")
    try:
        rc, out = run_cli([
            "--config", "/root/reference/v1_api_demo/quick_start/"
            "trainer_config.lr.py",
            "--job", "train", "--num_passes", str(passes),
            "--test_period", "1", "--log_period", "0"])
    finally:
        os.chdir(cwd)
        sys.path.remove("/root/reference/v1_api_demo/quick_start")
    return {
        "config": "v1_api_demo/quick_start/trainer_config.lr.py + "
                  "dataprovider_bow.py (both unmodified)",
        "corpus": "REAL checked-in CoNLL-2000 sentences (209 train / 36 "
                  "held-out); derived binary label = sentence contains "
                  "a VBD token (demo's Amazon corpus needs egress)",
        "rc": rc, "passes": passes,
        "final_train_error": last_metric(out, r"Pass \d+:",
                                         "classification_error"),
        "heldout_test_error": last_metric(out, r"Test:",
                                          "classification_error"),
        "wall_s": round(time.time() - t0, 1),
    }


def job_seq2seq_transduction(passes: int):
    """The NMT north-star model family (models/seq2seq.py attention
    seq2seq — generation goldens vs rnn_gen_test_model_dir live in
    test_reference_model_golden) TRAINED on real data: word->POS
    sequence transduction over the checked-in CoNLL-2000 slice. No
    parallel bilingual corpus is checked into the reference, so the
    held-out metric is next-token prediction accuracy on unseen
    sentences (teacher-forced, mask-weighted)."""
    import numpy as np

    import jax.numpy as jnp

    from paddle_tpu.config import dsl
    from paddle_tpu.core.argument import Argument
    from paddle_tpu.models import seq2seq_attention
    from paddle_tpu.optim import Adam
    from paddle_tpu.trainer import events as ev
    from paddle_tpu.trainer.trainer import SGD

    t0 = time.time()
    train = list(_conll_sentences(CONLL_TRAIN))
    test = list(_conll_sentences(CONLL_TEST))
    counts = {}
    for s in train:
        for w in s:
            counts[w[0]] = counts.get(w[0], 0) + 1
    word_id = {w: i + 1 for i, w in enumerate(
        sorted(w for w, c in counts.items() if c >= 2))}  # 0 = UNK
    tags = sorted({w[1] for s in train for w in s})
    # 0=<s>, 1=</s>, 2=<unk-tag> (held-out-only tags map to a RESERVED id
    # the model never saw in training, so those positions count as
    # errors — never as free hits on a real tag)
    tag_id = {t: i + 3 for i, t in enumerate(tags)}
    src_vocab = len(word_id) + 1
    trg_vocab = len(tags) + 3
    max_t = 52

    def encode(sents):
        B = len(sents)
        src = np.zeros((B, max_t), np.int32)
        trg_full = np.zeros((B, max_t + 1), np.int32)   # starts with <s>
        trg_next = np.ones((B, max_t + 1), np.int32)    # ends with </s>
        m_s = np.zeros((B, max_t), np.float32)
        m_t = np.zeros((B, max_t + 1), np.float32)
        for i, s in enumerate(sents):
            n = min(len(s), max_t)
            ids = [word_id.get(w[0], 0) for w in s[:n]]
            tgs = [tag_id.get(w[1], 2) for w in s[:n]]
            src[i, :n] = ids
            m_s[i, :n] = 1.0
            trg_full[i, 1: n + 1] = tgs
            trg_next[i, :n] = tgs
            trg_next[i, n] = 1
            m_t[i, : n + 1] = 1.0
        return src, trg_full, trg_next, m_s, m_t

    def reader():
        order = np.random.RandomState(0).permutation(len(train))
        for i in range(0, len(order), 16):
            batch = [train[j] for j in order[i: i + 16]]
            src, tf, tn, ms, mt = encode(batch)
            yield {"source_words": Argument(value=jnp.asarray(src),
                                            mask=jnp.asarray(ms)),
                   "target_words": Argument(value=jnp.asarray(tf),
                                            mask=jnp.asarray(mt)),
                   "target_next": Argument(value=jnp.asarray(tn),
                                           mask=jnp.asarray(mt))}

    dsl.reset()
    cost, probs, _ = seq2seq_attention(
        src_vocab=src_vocab, trg_vocab=trg_vocab, embed_dim=64, hidden=64)
    tr = SGD(cost=cost, update_equation=Adam(learning_rate=2e-3),
             extra_layers=[probs])
    costs = []
    tr.train(reader, num_passes=passes,
             event_handler=lambda e: costs.append(float(e.cost))
             if isinstance(e, ev.EndIteration) else None)

    # held-out teacher-forced next-token accuracy
    src, tf, tn, ms, mt = encode(test)
    outs = tr.network.apply(
        tr.params, {"source_words": Argument(value=jnp.asarray(src),
                                             mask=jnp.asarray(ms)),
                    "target_words": Argument(value=jnp.asarray(tf),
                                             mask=jnp.asarray(mt)),
                    "target_next": Argument(value=jnp.asarray(tn),
                                            mask=jnp.asarray(mt))},
        train=False)
    pred = np.asarray(jnp.argmax(outs[probs.name].value, axis=-1))
    acc = float((np.asarray(pred) == tn)[mt > 0].mean())
    return {
        "config": "models/seq2seq.py seq2seq_attention (the NMT family; "
                  "generation goldens in test_reference_model_golden)",
        "corpus": "REAL checked-in CoNLL-2000 slice; word->POS sequence "
                  "transduction (no parallel bilingual corpus is checked "
                  "into the reference; caveat recorded)",
        "rc": 0, "passes": passes,
        "first_train_cost": round(costs[0], 4) if costs else None,
        "final_train_cost": round(costs[-1], 4) if costs else None,
        "heldout_next_token_accuracy": round(acc, 4),
        "wall_s": round(time.time() - t0, 1),
    }


def run_cli(argv):
    from paddle_tpu.trainer import cli
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli.main(argv)
    out = buf.getvalue()
    sys.stdout.write(out)
    return rc, out


def last_metric(out: str, line_prefix: str, key: str):
    vals = [float(m.group(1)) for m in re.finditer(
        rf"{line_prefix}.*{key}=([0-9.eE+-]+)", out)]
    return vals[-1] if vals else None


def job_light(workdir: str, passes: int):
    """light_mnist.py: the demo's lighter conv config (Adam), same
    split + held-out eval."""
    install_provider_shim()
    t0 = time.time()
    cwd = os.getcwd()
    os.chdir(workdir)
    try:
        rc, out = run_cli([
            "--config", "/root/reference/v1_api_demo/mnist/light_mnist.py",
            "--job", "train", "--num_passes", str(passes),
            "--test_period", "1", "--log_period", "0"])
    finally:
        os.chdir(cwd)
    train_err = last_metric(out, r"Pass \d+:", "classification_error")
    test_err = last_metric(out, r"Test:", "classification_error")
    return {
        "config": "v1_api_demo/mnist/light_mnist.py (unmodified; "
                  "user-side mnist_provider reads the proto shard)",
        "corpus": "mnist_bin_part split 827 train / 400 held-out",
        "rc": rc, "passes": passes,
        "final_train_error": train_err,
        "heldout_test_error": test_err,
        "heldout_test_accuracy": None if test_err is None
        else round(1 - test_err, 4),
        "wall_s": round(time.time() - t0, 1),
    }


def job_vgg(workdir: str, passes: int):
    install_provider_shim()
    t0 = time.time()
    cwd = os.getcwd()
    os.chdir(workdir)
    try:
        rc, out = run_cli([
            "--config", VGG_CONFIG,
            "--job", "train", "--num_passes", str(passes),
            "--test_period", "1", "--log_period", "0"])
    finally:
        os.chdir(cwd)
    train_err = last_metric(out, r"Pass \d+:", "classification_error")
    test_err = last_metric(out, r"Test:", "classification_error")
    return {
        "config": "v1_api_demo/mnist/vgg_16_mnist.py (unmodified; "
                  "user-side mnist_provider reads the proto shard)",
        "corpus": "mnist_bin_part split 827 train / 400 held-out",
        "rc": rc, "passes": passes,
        "final_train_error": train_err,
        "heldout_test_error": test_err,
        "heldout_test_accuracy": None if test_err is None
        else round(1 - test_err, 4),
        "wall_s": round(time.time() - t0, 1),
    }


def main():
    import jax

    # sitecustomize pre-imports jax with the axon backend, so the
    # JAX_PLATFORMS env var alone does not stick; honor it explicitly
    # (otherwise a wedged TPU tunnel hangs even CPU-intended runs)
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    platform = jax.devices()[0].platform
    workdir = os.path.abspath(os.environ.get("ACC_WORKDIR",
                                             "/tmp/paddle_tpu_accuracy"))
    os.makedirs(workdir, exist_ok=True)
    n = split_shard(workdir)
    out_json = os.environ.get("ACC_OUT", "ACCURACY_r05.json")
    report = {
        "platform": platform,
        "corpus_note": (
            f"only real MNIST on this offline host is the reference's "
            f"checked-in shard ({n} samples, ~2% of MNIST); the demo "
            "data download scripts need network egress. Reference-grade "
            "full-corpus accuracy is not reachable from it; this "
            "artifact shows the unmodified configs training real data "
            "end-to-end. The three sequence/text entries run on the "
            "REAL CoNLL-2000 slice checked into paddle/trainer/tests "
            "(5000 train / 1000 held-out lines)."),
    }

    def _save():
        json.dump(report, open(out_json, "w"), indent=1)

    # cheapest jobs first so a partial run still carries evidence
    report["sequence_tagging_rnn_crf"] = job_sequence_tagging(
        os.path.join(workdir, "tag"),
        int(os.environ.get("ACC_TAG_PASSES", "30")))
    _save()
    report["quick_start_ctr_lr"] = job_quick_start_ctr(
        os.path.join(workdir, "ctr"),
        int(os.environ.get("ACC_CTR_PASSES", "40")))
    _save()
    report["seq2seq_word_to_pos"] = job_seq2seq_transduction(
        int(os.environ.get("ACC_S2S_PASSES", "30")))
    _save()
    report["light_mnist"] = job_light(
        workdir, int(os.environ.get("ACC_LIGHT_PASSES", "40")))
    _save()
    report["vgg_16_mnist"] = job_vgg(
        workdir, int(os.environ.get("ACC_VGG_PASSES", "60")))
    _save()
    print(json.dumps(report, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
