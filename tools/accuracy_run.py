"""North-star accuracy evidence (ACCURACY_r04.json).

Trains reference configs UNMODIFIED through the CLI on the only real
MNIST corpus present in this offline environment: the reference's own
checked-in proto shard (``paddle/trainer/tests/mnist_bin_part``, 1227
genuine MNIST digits — the download scripts in ``v1_api_demo/mnist/data``
need network egress this machine does not have).

Jobs (both on a 1100/127 train/held-out split of the real shard, with
per-pass held-out evaluation; the user-side data provider module
(``mnist_provider`` — user code in the demo) is substituted with one
that reads the proto shard; the CONFIGS — network, optimizer, batch
size, regularization — run unmodified):
1. ``v1_api_demo/mnist/light_mnist.py`` (conv groups + Adam).
2. ``v1_api_demo/mnist/vgg_16_mnist.py`` (small_vgg + Momentum,
   the north-star demo config).

Honest caveat recorded in the artifact: 1227 samples is ~2% of MNIST;
reference-grade (99%+) test accuracy requires the full 60k corpus,
which cannot be downloaded here. The evidence shows the training
pipeline drives real data to high accuracy, not full-corpus parity.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import re
import sys
import time
import types

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REF_TESTS = "/root/reference/paddle/trainer/tests"
VGG_CONFIG = "/root/reference/v1_api_demo/mnist/vgg_16_mnist.py"


def split_shard(workdir: str):
    """mnist_bin_part -> 1100-sample train shard + 127-sample test shard
    with the demo's data/{train,test}.list layout."""
    import numpy as np

    from paddle_tpu.data.protodata import read_messages, write_shard
    header, samples = read_messages(os.path.join(REF_TESTS,
                                                 "mnist_bin_part"))
    samples = list(samples)
    # the shard is label-sorted — a tail split would hold out a class
    # the training set barely contains; shuffle deterministically
    order = np.random.RandomState(0).permutation(len(samples))
    samples = [samples[i] for i in order]
    os.makedirs(os.path.join(workdir, "data"), exist_ok=True)
    train_p = os.path.join(workdir, "data", "train.shard")
    test_p = os.path.join(workdir, "data", "test.shard")
    write_shard(train_p, header, samples[:1100])
    write_shard(test_p, header, samples[1100:])
    with open(os.path.join(workdir, "data", "train.list"), "w") as f:
        f.write(train_p + "\n")
    with open(os.path.join(workdir, "data", "test.list"), "w") as f:
        f.write(test_p + "\n")
    return len(samples)


def install_provider_shim():
    """A ``mnist_provider`` module reading proto shards with the demo
    provider's exact interface (pixel scaled to [-1, 1] like
    ``mnist_util.read_from_mnist``)."""
    from paddle_tpu.compat import install_paddle_alias
    install_paddle_alias()
    from paddle.trainer.PyDataProvider2 import (dense_vector,  # noqa
                                                integer_value, provider)

    mod = types.ModuleType("mnist_provider")

    @provider(input_types={"pixel": dense_vector(28 * 28),
                           "label": integer_value(10)})
    def process(settings, filename):
        from paddle_tpu.data.protodata import ProtoDataReader
        for pixel, label in ProtoDataReader([filename])():
            yield {"pixel": pixel * 2.0 - 1.0, "label": int(label)}

    mod.process = process
    sys.modules["mnist_provider"] = mod
    return mod


def run_cli(argv):
    from paddle_tpu.trainer import cli
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli.main(argv)
    out = buf.getvalue()
    sys.stdout.write(out)
    return rc, out


def last_metric(out: str, line_prefix: str, key: str):
    vals = [float(m.group(1)) for m in re.finditer(
        rf"{line_prefix}.*{key}=([0-9.eE+-]+)", out)]
    return vals[-1] if vals else None


def job_light(workdir: str, passes: int):
    """light_mnist.py: the demo's lighter conv config (Adam), same
    split + held-out eval."""
    install_provider_shim()
    t0 = time.time()
    cwd = os.getcwd()
    os.chdir(workdir)
    try:
        rc, out = run_cli([
            "--config", "/root/reference/v1_api_demo/mnist/light_mnist.py",
            "--job", "train", "--num_passes", str(passes),
            "--test_period", "1", "--log_period", "0"])
    finally:
        os.chdir(cwd)
    train_err = last_metric(out, r"Pass \d+:", "classification_error")
    test_err = last_metric(out, r"Test:", "classification_error")
    return {
        "config": "v1_api_demo/mnist/light_mnist.py (unmodified; "
                  "user-side mnist_provider reads the proto shard)",
        "corpus": "mnist_bin_part split 1100 train / 127 held-out",
        "rc": rc, "passes": passes,
        "final_train_error": train_err,
        "heldout_test_error": test_err,
        "heldout_test_accuracy": None if test_err is None
        else round(1 - test_err, 4),
        "wall_s": round(time.time() - t0, 1),
    }


def job_vgg(workdir: str, passes: int):
    install_provider_shim()
    t0 = time.time()
    cwd = os.getcwd()
    os.chdir(workdir)
    try:
        rc, out = run_cli([
            "--config", VGG_CONFIG,
            "--job", "train", "--num_passes", str(passes),
            "--test_period", "1", "--log_period", "0"])
    finally:
        os.chdir(cwd)
    train_err = last_metric(out, r"Pass \d+:", "classification_error")
    test_err = last_metric(out, r"Test:", "classification_error")
    return {
        "config": "v1_api_demo/mnist/vgg_16_mnist.py (unmodified; "
                  "user-side mnist_provider reads the proto shard)",
        "corpus": "mnist_bin_part split 1100 train / 127 held-out",
        "rc": rc, "passes": passes,
        "final_train_error": train_err,
        "heldout_test_error": test_err,
        "heldout_test_accuracy": None if test_err is None
        else round(1 - test_err, 4),
        "wall_s": round(time.time() - t0, 1),
    }


def main():
    import jax

    # sitecustomize pre-imports jax with the axon backend, so the
    # JAX_PLATFORMS env var alone does not stick; honor it explicitly
    # (otherwise a wedged TPU tunnel hangs even CPU-intended runs)
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    platform = jax.devices()[0].platform
    workdir = os.path.abspath(os.environ.get("ACC_WORKDIR",
                                             "/tmp/paddle_tpu_accuracy"))
    os.makedirs(workdir, exist_ok=True)
    n = split_shard(workdir)
    report = {
        "platform": platform,
        "corpus_note": (
            f"only real MNIST on this offline host is the reference's "
            f"checked-in shard ({n} samples, ~2% of MNIST); the demo "
            "data download scripts need network egress. Reference-grade "
            "full-corpus accuracy is not reachable from it; this "
            "artifact shows the unmodified configs training real data "
            "end-to-end."),
        "light_mnist": job_light(
            workdir, int(os.environ.get("ACC_LIGHT_PASSES", "30"))),
    }
    json.dump(report, open("ACCURACY_r04.json", "w"), indent=1)
    report["vgg_16_mnist"] = job_vgg(
        workdir, int(os.environ.get("ACC_VGG_PASSES", "30")))
    json.dump(report, open("ACCURACY_r04.json", "w"), indent=1)
    print(json.dumps(report, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
