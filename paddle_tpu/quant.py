"""Deploy-time weight quantization (the serving precision tier).

``--job=merge --quantize=bf16|int8`` calls :func:`quantize_params` and
writes the result into the PTM1 artifact as an optional ``quant``
section (``trainer/merge_model.py`` — an old reader of an unquantized
file sees no change). The serving predictor reverses it lazily:
quantized leaves stay in their storage dtype in HBM (int8 weights ARE
int8 device arrays) and :func:`materialize` rebuilds the compute-dtype
view *inside* the jitted forward, so XLA fuses each dequant convert
into its consumer instead of materializing an f32 copy of the model —
the whole point of the exercise (graftlint pass 5 pins the
``serving_quant`` program's per-device bytes so a regression back to
f32 residents is PT602 drift, not a hope).

Scheme:

- **bf16** — storage cast, no scales. Every floating leaf is kept as
  bfloat16 and converted back to f32 at point of use.
- **int8** — per-tensor symmetric: ``scale = max|w| / 127`` (a
  zero-range/constant tensor pins ``scale = 1`` — no div-by-zero, the
  quantized zeros round-trip exactly), ``q = clip(round(w / scale))``.
  Tables with sparse gradients quantize **row-wise** (one scale per
  leading row, so a hot row's range cannot be crushed by a cold
  outlier row); a sparse table row-wise cannot express (ndim < 2)
  stands down to f32 with a named entry in ``meta["skipped"]`` — never
  silently. 1-D leaves (biases, norm gains: a rounding error there
  shifts every logit) and non-float leaves also stay f32/as-is, also
  named in ``skipped``.

Masks never enter this module: quantization sees the parameter table
only, and the feed funnel keeps its f32-mask invariant
(``utils/masks.assert_feed_masks_f32``, graftlint PT102/PT203).

The gate half lives here too: :func:`make_golden_rows` +
:func:`golden_section` record a deterministic golden-request set with
fp32 reference outputs at merge time; the predictor replays it at
warmup and refuses READY past the per-dtype tolerance
(:data:`GATE_TOLERANCES`, override via ``--quantize_tol``).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from paddle_tpu.utils.log import get_logger

logger = get_logger("quant")

QUANT_DTYPES = ("bf16", "int8")

#: warmup-gate tolerance on the normalized max-abs output delta
#: (|quant - fp32|_max / max(1, |fp32|_max)), per storage dtype.
#: bf16 keeps 8 mantissa bits (~2-3 significant digits); int8
#: per-tensor rounding is an order coarser.
GATE_TOLERANCES = {"bf16": 2e-2, "int8": 1e-1}

#: params-dict key suffix the predictor uses for traced scale leaves
#: (they ride the same pytree as the weights so they are jit ARGUMENTS,
#: never closed-over constants — graftlint PT101/PT201 discipline).
SCALE_SUFFIX = "::scale"


def _is_float(arr) -> bool:
    return np.issubdtype(np.asarray(arr).dtype, np.floating)


def int8_scale(w: np.ndarray, axis=None) -> np.ndarray:
    """Symmetric per-tensor (``axis=None``) or per-row scale with the
    zero-range guard: a constant/empty range pins scale=1 so the
    quantized zeros round-trip exactly and nothing divides by zero."""
    amax = np.max(np.abs(w), axis=axis, keepdims=axis is not None)
    amax = np.asarray(amax, np.float32)
    return np.where(amax > 0, amax / 127.0, np.float32(1.0))


def quantize_params(params: Dict[str, np.ndarray], dtype: str,
                    sparse_names: Iterable[str] = ()
                    ) -> Tuple[Dict[str, np.ndarray], Dict]:
    """-> ``(qparams, meta)``. ``meta`` is the PTM1 ``quant`` section:
    ``{"dtype", "scales": {name: np f32}, "skipped": {name: reason},
    "tol"}``. ``sparse_names`` (from ``trainer.meta``'s
    ``ParamSpec.sparse_grad``) selects row-wise int8 scales."""
    if dtype not in QUANT_DTYPES:
        raise ValueError(f"--quantize must be one of {QUANT_DTYPES}, "
                         f"got {dtype!r}")
    sparse = set(sparse_names)
    qparams: Dict[str, np.ndarray] = {}
    scales: Dict[str, np.ndarray] = {}
    skipped: Dict[str, str] = {}
    for name, v in params.items():
        w = np.asarray(v)
        if not _is_float(w):
            qparams[name] = w
            skipped[name] = f"non-float dtype {w.dtype}"
            continue
        if dtype == "bf16":
            import jax.numpy as jnp
            qparams[name] = np.asarray(
                jnp.asarray(w, jnp.float32).astype(jnp.bfloat16))
            continue
        # int8
        if w.ndim < 2:
            qparams[name] = np.asarray(w, np.float32)
            skipped[name] = (
                "sparse table with ndim < 2: row-wise int8 scales are "
                "not expressible, kept f32" if name in sparse else
                "1-D leaf (bias/norm) kept f32: per-element rounding "
                "would shift every logit")
            if name in sparse:
                logger.warning("quantize: %s STOOD DOWN to f32 (%s)",
                               name, skipped[name])
            continue
        axis = tuple(range(1, w.ndim)) if name in sparse else None
        s = int8_scale(w.astype(np.float32), axis=axis)
        q = np.clip(np.rint(w.astype(np.float32) / s), -127, 127)
        qparams[name] = q.astype(np.int8)
        scales[name] = np.asarray(s, np.float32)
    meta = {"dtype": dtype, "scales": scales, "skipped": skipped,
            "tol": GATE_TOLERANCES[dtype]}
    return qparams, meta


def scale_leaves(meta: Dict) -> Dict[str, np.ndarray]:
    """The traced scale leaves, keyed for the predictor's params dict
    (``name + SCALE_SUFFIX``). Empty for bf16."""
    return {name + SCALE_SUFFIX: s
            for name, s in meta.get("scales", {}).items()}


def materialize(params: Dict, meta: Dict,
                compute_dtype=None) -> Dict:
    """The compute-dtype view of a quantized params dict, built INSIDE
    a trace: int8 leaves dequantize against their traced
    ``name::scale`` sibling, bf16 leaves upcast, f32 stand-downs pass
    through, scale keys are stripped. All ops are elementwise converts
    XLA fuses into each weight's consumer — no f32 twin of the model
    ever becomes a resident buffer."""
    import jax.numpy as jnp
    compute_dtype = compute_dtype or jnp.float32
    out = {}
    for name, leaf in params.items():
        if name.endswith(SCALE_SUFFIX):
            continue
        skey = name + SCALE_SUFFIX
        if skey in params:
            out[name] = (leaf.astype(compute_dtype)
                         * params[skey].astype(compute_dtype))
        elif jnp.issubdtype(leaf.dtype, jnp.floating) \
                and leaf.dtype != compute_dtype:
            out[name] = leaf.astype(compute_dtype)
        else:
            out[name] = leaf
    return out


def dequantize_params(qparams: Dict[str, np.ndarray],
                      meta: Dict) -> Dict[str, np.ndarray]:
    """Host-side eager dequant (tests / offline tooling): the same
    arithmetic as :func:`materialize`, on numpy."""
    out = {}
    scales = meta.get("scales", {})
    for name, v in qparams.items():
        w = np.asarray(v)
        if name in scales:
            out[name] = w.astype(np.float32) * np.asarray(scales[name],
                                                          np.float32)
        elif _is_float(w):
            out[name] = w.astype(np.float32)
        else:
            out[name] = w
    return out


# ------------------------------------------------------------- golden set
def make_golden_rows(feeding: Dict, n: int = 4, length: int = 4,
                     seed: int = 7) -> List[tuple]:
    """A deterministic pseudo-random golden-request set shaped like
    real traffic for every input slot (dense values, in-range ids,
    sparse index lists). Short sequences (``length``) so the set stays
    admissible under any serving length-bucket menu."""
    from paddle_tpu.data import types as T
    rng = np.random.RandomState(seed)
    rows: List[tuple] = []
    for _ in range(n):
        row = []
        for name in feeding:
            itype = feeding[name]
            if itype.seq_type == T.SUB_SEQUENCE:
                raise ValueError(
                    f"golden set: input {name!r} is a nested sequence; "
                    "serving refuses SUB_SEQUENCE inputs, so a "
                    "quantized artifact cannot gate on one")
            steps = length if itype.seq_type == T.SEQUENCE else None

            def one():
                if itype.type == T.INDEX:
                    return int(rng.randint(itype.dim))
                if itype.type in (T.SPARSE_BINARY, T.SPARSE_FLOAT):
                    k = min(2, itype.dim)
                    ids = sorted(rng.choice(itype.dim, size=k,
                                            replace=False).tolist())
                    if itype.type == T.SPARSE_FLOAT:
                        return list(zip(
                            ids, rng.rand(k).astype(float).tolist()))
                    return ids
                return rng.randn(itype.dim).astype(np.float32)

            row.append([one() for _ in range(steps)]
                       if steps is not None else one())
        rows.append(tuple(row))
    return rows


def golden_section(graph, params: Dict, output_names: List[str],
                   feeding: Dict, n: int = 4) -> Optional[Dict]:
    """The PTM1 ``golden`` section: rows + their fp32 reference
    outputs, computed on the UNQUANTIZED params through the plain
    (unbucketed) feed path. Returns None (with a named warning) for a
    generation-only config — the gate covers score outputs."""
    from paddle_tpu.core.network import Network
    from paddle_tpu.data.feeder import DataFeeder
    score = [name for name in output_names
             if graph.layers[name].type != "beam_search_group"]
    if not score:
        logger.warning(
            "quantize: config has no scoring outputs (generation-only)"
            " — no golden gate set recorded; the warmup gate will "
            "stand down with a named warning")
        return None
    rows = make_golden_rows(feeding, n=n)
    feed = DataFeeder(feeding)(list(rows))
    outs = Network(graph, outputs=score).apply(params, feed, train=False)
    refs = {name: np.asarray(outs[name].value) for name in score}
    return {"rows": rows, "outputs": refs, "n": n}


def gate_delta(got: np.ndarray, ref: np.ndarray) -> float:
    """Normalized max-abs output delta the warmup gate compares against
    the per-dtype tolerance."""
    got = np.asarray(got, np.float64)
    ref = np.asarray(ref, np.float64)
    return float(np.max(np.abs(got - ref))
                 / max(1.0, float(np.max(np.abs(ref)))))
