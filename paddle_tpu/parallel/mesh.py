"""Device mesh + sharding utilities.

This module is the TPU-native replacement for the reference's entire
parallel/communication stack:

- intra-node data parallelism: ``MultiGradientMachine``'s thread-per-device
  ring scatter/gather (``MultiGradientMachine.h:44-80``) becomes a batch
  sharded over the mesh ``data`` axis; XLA emits the gradient all-reduce
  (psum) over ICI.
- multi-node sync SGD: ``ParameterServer2::addGradient``
  (``ParameterServer2.cpp:362``) + pass barriers become the same all-reduce
  — sync SGD *is* all-reduce semantics.
- sparse/model-parallel embeddings: ``SparseRowMatrix``-style row slices
  (``SparseRowMatrix.h:204``) become embedding tables sharded on the
  ``model`` axis, gathered by XLA all-to-all/all-gather.
- async SGD (``ParameterServer2.cpp:457``): not representable on a
  synchronous fabric; executed as sync SGD (documented approximation,
  SURVEY §2 checklist).

Axes: ``data`` (batch), ``model`` (tensor/embedding sharding). Multi-host
DCN maps to extra leading mesh dims transparently through jax.devices().
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.core.argument import Argument

DATA_AXIS = "data"
MODEL_AXIS = "model"


def create_mesh(n_data: Optional[int] = None, n_model: int = 1,
                devices=None) -> Mesh:
    """Build a (data, model) mesh. Defaults to all visible devices on the
    data axis (pure DP, the reference's trainer_count semantics)."""
    devices = devices if devices is not None else jax.devices()
    if n_data is None:
        n_data = len(devices) // n_model
    devs = np.asarray(devices[: n_data * n_model]).reshape(n_data, n_model)
    return Mesh(devs, (DATA_AXIS, MODEL_AXIS))


def shard_batch(feed: Dict[str, Argument], mesh: Mesh) -> Dict[str, Argument]:
    """Place a feed dict with the batch dim split over the data axis."""

    def place(x):
        spec = P(DATA_AXIS, *([None] * (x.ndim - 1)))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(place, feed)


def replicate(tree, mesh: Mesh):
    """Replicate a pytree (params/opt state) across the mesh."""
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P())), tree)


def shard_params(params: Dict[str, jax.Array], mesh: Mesh,
                 rules: Optional[Dict[str, P]] = None):
    """Place parameters: replicated by default; ``rules`` maps param-name
    substrings to PartitionSpecs (e.g. shard embedding rows on MODEL_AXIS,
    the sparse-embedding model parallelism of SURVEY §2 #5)."""
    out = {}
    for name, p in params.items():
        spec = P()
        if rules:
            for pat, s in rules.items():
                if pat in name:
                    spec = s
                    break
        out[name] = jax.device_put(p, NamedSharding(mesh, spec))
    return out
