"""Device mesh + sharding utilities.

This module is the TPU-native replacement for the reference's entire
parallel/communication stack:

- intra-node data parallelism: ``MultiGradientMachine``'s thread-per-device
  ring scatter/gather (``MultiGradientMachine.h:44-80``) becomes a batch
  sharded over the mesh ``data`` axis; XLA emits the gradient all-reduce
  (psum) over ICI.
- multi-node sync SGD: ``ParameterServer2::addGradient``
  (``ParameterServer2.cpp:362``) + pass barriers become the same all-reduce
  — sync SGD *is* all-reduce semantics.
- sparse/model-parallel embeddings: ``SparseRowMatrix``-style row slices
  (``SparseRowMatrix.h:204``) become embedding tables sharded on the
  ``model`` axis, gathered by XLA all-to-all/all-gather.
- async SGD (``ParameterServer2.cpp:457``): not representable on a
  synchronous fabric; executed as sync SGD (documented approximation,
  SURVEY §2 checklist).

Axes: ``data`` (batch), ``fsdp`` (batch + flat-packed parameter/optimizer
state, 1/N per device — ``optim/zero1.py:FsdpUpdater``), ``model``
(tensor/embedding sharding), ``seq`` (sequence parallelism), ``pipe``
(GPipe stages). Multi-host DCN maps to extra leading mesh dims
transparently through jax.devices().

Since r17 the canonical placement derivations (batch/param/slot/packed
specs, the non-divisible replicated fallback) live in ONE object —
``parallel/layout.py:SpecLayout`` — and the placement helpers below
(``shard_params``/``param_shardings``/``shard_opt_state``) are thin
compatibility wrappers over it (``docs/spec_layout.md``).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.core.argument import Argument

DATA_AXIS = "data"
FSDP_AXIS = "fsdp"  # batch + flat-packed param/slot shards (zero1.py)
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"  # GPipe stage axis (parallel/pipeline.py)
DCN_AXIS = "dcn"  # cross-slice (data-center network) leading axis


def create_mesh(n_data: Optional[int] = None, n_model: int = 1,
                n_seq: int = 1, devices=None, n_pipe: int = 1,
                n_fsdp: int = 1) -> Mesh:
    """Build a (data, model) mesh — or (data, seq, model) when
    ``n_seq > 1`` for sequence/context parallelism (ring/ulysses
    attention shards the time axis over ``seq``; the axis sits between
    data and model so its ppermute/all-to-all rides ICI next to the
    model axis), or (data, pipe) when ``n_pipe > 1`` for pipeline
    parallelism (one GPipe stage per pipe slot, innermost so the
    stage-handoff ppermute rides ICI; ``--parallel_nn``,
    ``trainer/trainer.py:enable_pipeline``). Defaults to all visible
    devices on the data axis (pure DP, the reference's trainer_count
    semantics).

    ``n_fsdp > 1`` inserts the ``fsdp`` axis right after ``data``: the
    batch shards over BOTH (DP degree = data × fsdp, the same rows/
    gradients story), while eligible parameters and optimizer slots
    live flat-packed 1/n_fsdp per device with gather-on-use
    (``--fsdp``, ``optim/zero1.py:FsdpUpdater``,
    ``docs/spec_layout.md``). The 4D composition forms are
    (data, fsdp, pipe), (data, fsdp, seq, pipe) and
    (data, fsdp, seq, model)."""
    devices = devices if devices is not None else jax.devices()
    if n_pipe > 1 and n_model > 1:
        raise ValueError(
            "n_pipe does not compose with n_model (a pipeline stage owns "
            "its whole layer; shard within a stage via shard_rules "
            "instead)")
    if n_data is None:
        n_data = len(devices) // (n_model * n_seq * n_pipe * n_fsdp)
    if n_pipe > 1:
        dims = [(DATA_AXIS, n_data)]
        if n_fsdp > 1:
            dims.append((FSDP_AXIS, n_fsdp))
        if n_seq > 1:
            dims.append((SEQ_AXIS, n_seq))
        dims.append((PIPE_AXIS, n_pipe))
        total = 1
        for _, sz in dims:
            total *= sz
        devs = np.asarray(devices[:total]).reshape(
            tuple(sz for _, sz in dims))
        return Mesh(devs, tuple(ax for ax, _ in dims))
    if n_fsdp > 1:
        if n_seq > 1 or n_model > 1:
            devs = np.asarray(
                devices[: n_data * n_fsdp * n_seq * n_model]).reshape(
                n_data, n_fsdp, n_seq, n_model)
            return Mesh(devs, (DATA_AXIS, FSDP_AXIS, SEQ_AXIS, MODEL_AXIS))
        devs = np.asarray(devices[: n_data * n_fsdp]).reshape(
            n_data, n_fsdp)
        return Mesh(devs, (DATA_AXIS, FSDP_AXIS))
    if n_seq > 1:
        devs = np.asarray(devices[: n_data * n_seq * n_model]).reshape(
            n_data, n_seq, n_model)
        return Mesh(devs, (DATA_AXIS, SEQ_AXIS, MODEL_AXIS))
    devs = np.asarray(devices[: n_data * n_model]).reshape(n_data, n_model)
    return Mesh(devs, (DATA_AXIS, MODEL_AXIS))


def create_multislice_mesh(n_slices: Optional[int] = None,
                           n_data: Optional[int] = None, n_model: int = 1,
                           devices=None) -> Mesh:
    """Build a hierarchical (dcn, data, model) mesh for multi-slice jobs —
    the TPU-native successor of the reference's multi-*node* story
    (`ParameterServer2` sharded sync SGD over TCP/RDMA,
    `ParameterServer2.cpp:362`; SURVEY §5.8).

    The batch is data-parallel over BOTH the leading ``dcn`` axis (slices,
    connected by data-center network) and the ``data`` axis (chips within a
    slice, connected by ICI); the gradient all-reduce XLA emits over such a
    mesh is hierarchical — reduce-scatter/all-gather rides ICI within each
    slice and only the per-slice partial crosses DCN. The ``model`` axis
    (tensor/embedding sharding, all-to-all traffic) is laid out innermost so
    its collectives never leave a slice.

    On real multi-slice hardware, devices are grouped by their
    ``slice_index`` attribute; elsewhere (virtual CPU meshes, single slice)
    a contiguous reshape stands in, which preserves the axis semantics the
    driver's dryrun validates.
    """
    devices = list(devices if devices is not None else jax.devices())
    by_slice: Dict[int, list] = {}
    for d in devices:
        by_slice.setdefault(getattr(d, "slice_index", 0), []).append(d)
    if n_slices is None:
        n_slices = len(by_slice) if len(by_slice) > 1 else 1
    if len(by_slice) > 1 and n_slices != len(by_slice):
        # never silently mix physical slices inside a dcn group — the
        # data/model axes would then carry "ICI" collectives across DCN
        raise ValueError(
            f"devices span {len(by_slice)} physical slices but "
            f"n_slices={n_slices}; pass n_slices={len(by_slice)} (or a "
            "device subset) so the dcn axis follows slice boundaries")
    if len(by_slice) == n_slices and n_slices > 1:
        per_slice = min(len(v) for v in by_slice.values())
        grouped = [v[:per_slice] for _, v in sorted(by_slice.items())]
    else:  # virtual: contiguous split into n_slices groups
        per_slice = len(devices) // n_slices
        grouped = [devices[i * per_slice:(i + 1) * per_slice]
                   for i in range(n_slices)]
    if n_data is None:
        n_data = per_slice // n_model
    if n_data * n_model > per_slice:
        raise ValueError(
            f"create_multislice_mesh: n_data ({n_data}) x n_model "
            f"({n_model}) = {n_data * n_model} exceeds the {per_slice} "
            f"devices available per slice")
    used = n_slices * n_data * n_model
    if used < len(devices):
        from paddle_tpu.utils.log import logger
        logger.warning(
            "create_multislice_mesh uses %d of %d devices "
            "(n_slices=%d x n_data=%d x n_model=%d); %d devices idle",
            used, len(devices), n_slices, n_data, n_model,
            len(devices) - used)
    devs = np.asarray([g[: n_data * n_model] for g in grouped]).reshape(
        n_slices, n_data, n_model)
    return Mesh(devs, (DCN_AXIS, DATA_AXIS, MODEL_AXIS))


def shard_map_compat(f, mesh: Mesh, in_specs, out_specs,
                     check_vma: bool = False):
    """``jax.shard_map`` across jax versions: newer jax exports it
    top-level with ``check_vma``; older jax has
    ``jax.experimental.shard_map`` with the same knob named
    ``check_rep``. One spelling for every shard_map consumer
    (pipeline/moe/ring)."""
    try:
        from jax import shard_map as _sm
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=check_vma)
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=check_vma)


def batch_axes(mesh: Mesh):
    """Mesh axes the batch dimension is split over (dcn is part of DP,
    and so is fsdp — FSDP devices carry independent batch rows exactly
    like plain DP; only the PARAMETER placement differs). A mesh
    WITHOUT a data axis (e.g. a pure ("pipe",) stage mesh) has no
    batch axes: the batch replicates and DP degree is 1."""
    if DATA_AXIS not in mesh.axis_names:
        return ()
    axes = []
    if DCN_AXIS in mesh.axis_names:
        axes.append(DCN_AXIS)
    axes.append(DATA_AXIS)
    if FSDP_AXIS in mesh.axis_names:
        axes.append(FSDP_AXIS)
    return tuple(axes)


def data_parallel_degree(mesh: Mesh) -> int:
    d = 1
    for ax in batch_axes(mesh):
        d *= mesh.shape[ax]
    return d


def batch_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """The NamedSharding a rank-``ndim`` batch array takes: dim 0 split
    over the data (+dcn) axes, the rest replicated. The single source of
    truth for batch placement — ``shard_batch`` and the async input
    pipeline's device_put stage (``data/prefetch.py``) both use it, so a
    prefetched batch lands exactly where the step expects it."""
    axes = batch_axes(mesh)
    if not axes:
        return NamedSharding(mesh, P())
    return NamedSharding(mesh, P(axes, *([None] * (ndim - 1))))


def shard_batch(feed: Dict[str, Argument], mesh: Mesh) -> Dict[str, Argument]:
    """Place a feed dict with the batch dim split over the data axis (and
    the dcn axis on a multi-slice mesh)."""

    n_data = data_parallel_degree(mesh)

    def place(x):
        if x.shape[0] % n_data != 0:
            raise ValueError(
                f"batch size {x.shape[0]} not divisible by data-parallel "
                f"degree {n_data}; pad or resize the batch (the reference "
                "splits remainders unevenly across TrainerThreads — on a "
                "SPMD mesh the split must be exact; DataFeeder "
                "batch_buckets pads with masked rows)")
        sharding = batch_sharding(mesh, x.ndim)
        if jax.process_count() > 1:
            # multi-host SPMD (dist.launch jobs): device_put cannot target
            # non-addressable devices; each process contributes the shards
            # it owns, sliced from the host-replicated batch by global
            # index
            host = np.asarray(x)
            return jax.make_array_from_callback(
                host.shape, sharding, lambda idx: host[idx])
        return jax.device_put(x, sharding)

    return jax.tree_util.tree_map(place, feed)


def replicate(tree, mesh: Mesh):
    """Replicate a pytree (params/opt state) across the mesh."""
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P())), tree)


def key_matches(pat: str, name: str) -> bool:
    """Does one rule key cover ``name``? A key starting with ``=``
    matches the full name EXACTLY (so a rule for ``_emb.w0`` can never
    capture ``_user_emb.w0``); any other key matches as a substring."""
    if pat.startswith("="):
        return pat[1:] == name
    return pat in name


def rule_key_for(name: str, rules: Optional[Dict[str, P]]
                 ) -> Optional[str]:
    """The key ``rule_for`` resolves ``name`` to, or None. Exact keys
    are consulted FIRST, then substring keys in table order — an
    ``=``-pin for one parameter always beats a broad substring rule,
    wherever it sits in the table (precedence pinned by
    tests/test_analysis.py; graftlint PT505's dead/shadowed-key
    analysis calls this same function, so the audit can never drift
    from the semantics it audits)."""
    if rules:
        for pat in rules:
            if pat.startswith("=") and key_matches(pat, name):
                return pat
        for pat in rules:
            if not pat.startswith("=") and key_matches(pat, name):
                return pat
    return None


def rule_for(name: str, rules: Optional[Dict[str, P]]) -> P:
    """First rule whose key matches ``name`` (see ``rule_key_for`` for
    the precedence contract); replicated default."""
    key = rule_key_for(name, rules)
    return rules[key] if key is not None else P()


def shard_params(params: Dict[str, jax.Array], mesh: Mesh,
                 rules: Optional[Dict[str, P]] = None):
    """Place parameters: replicated by default; ``rules`` maps param-name
    substrings to PartitionSpecs (e.g. shard embedding rows on MODEL_AXIS,
    the sparse-embedding model parallelism of SURVEY §2 #5).
    Compatibility wrapper over ``SpecLayout.place_params`` — the rules
    passed here are assumed already effective (the trainer builds them
    through its layout)."""
    from paddle_tpu.parallel.layout import SpecLayout
    return SpecLayout(mesh, rules=rules).place_params(params)


def param_shardings(param_names, mesh: Mesh,
                    rules: Optional[Dict[str, P]] = None):
    """NamedSharding per parameter name (for jit out_shardings so big
    sharded tables are *created* in place, never materialized whole).

    ``param_names`` may be a {name: ParamSpec} dict: parameters flagged
    ``sparse_grad`` (embedding tables) default to row-sharding over the
    model axis when no explicit rule names them — the ``SparseRowMatrix``
    row-slice placement, without configs having to spell it out.
    Compatibility wrapper over ``SpecLayout.param_shardings``."""
    from paddle_tpu.parallel.layout import SpecLayout
    layout = SpecLayout(mesh, param_specs=param_names, rules=rules)
    return layout.param_shardings(param_names)


def effective_rules(param_specs, mesh: Mesh,
                    rules: Optional[Dict[str, P]] = None) -> Dict[str, P]:
    """User rules + the sparse default: tables flagged ``sparse_grad`` with
    no explicit rule row-shard over the model axis. Use the result for both
    param placement and shard_opt_state so slots follow their table."""
    out = dict(rules or {})
    if not isinstance(param_specs, dict):
        return out
    if mesh.shape.get(MODEL_AXIS, 1) <= 1:
        return out
    for name, spec in param_specs.items():
        # guard on "no key matches", NOT on rule_for(...) == P(): a
        # user's explicit P() replication rule must win over the
        # sparse default (same contract as device_attr_rules), and
        # under exact-first precedence an auto-added "=" pin would
        # otherwise override the user's substring rule
        if getattr(spec, "sparse_grad", False) \
                and rule_key_for(name, out) is None:
            out["=" + name] = P(MODEL_AXIS)  # exact: no substring capture
    return out


def device_attr_rules(graph, param_specs, mesh: Mesh,
                      rules: Optional[Dict[str, P]] = None) -> Dict[str, P]:
    """The reference's per-layer ``device`` placement, TPU-native.

    Under ``--parallel_nn`` the reference pins whole layers to devices and
    runs them on per-device worker threads (``ParallelNeuralNetwork.h:
    23-62``, per-layer ``device`` attr in the config). Pinning layers to
    chips is an anti-pattern under SPMD — the XLA-native equivalent of
    "this layer lives on other devices" is sharding its parameters over
    the model axis and letting XLA insert the collectives the reference's
    task queues hand-scheduled. So: every layer whose config carries a
    nonnegative ``device`` gets its parameters sharded over MODEL_AXIS on
    their last (output-feature) dim. Explicit user rules win; parameters
    whose last dim doesn't divide the axis stay replicated (placement is
    a hint, not a contract)."""
    out = dict(rules or {})
    n_model = mesh.shape.get(MODEL_AXIS, 1)
    if graph is None or n_model <= 1 or not isinstance(param_specs, dict):
        return out
    pinned = {name for name, ldef in graph.layers.items()
              if int(getattr(ldef, "attrs", {}).get("device", -1)) >= 0}
    if not pinned:
        return out
    # the SAME config field also spells GPipe stages (pipeline.py:
    # make_pipeline_from_device_attrs). A pipeline config pins EVERY
    # non-data layer with contiguous stage ids from 0 — stand down so
    # the trainer doesn't silently model-shard stage ids; the
    # --parallel_nn shard-hint form pins only SOME layers.
    non_data = [n for n, l in graph.layers.items() if l.type != "data"]
    if non_data and set(non_data) <= pinned:
        stage_ids = sorted({int(graph.layers[n].attrs.get("device"))
                            for n in non_data})
        if len(stage_ids) > 1 and \
                stage_ids == list(range(len(stage_ids))):
            # a user who meant --parallel_nn shard hints (not GPipe
            # stages) must be able to see why they were ignored
            from paddle_tpu.utils.log import logger as _logger
            _logger.warning(
                "device_attr_rules: every non-data layer carries a "
                "contiguous device id 0..%d — treating the config as a "
                "pipeline-stage spelling and standing down the model-axis "
                "shard hints. If you meant --parallel_nn-style placement "
                "hints, leave at least one non-data layer unpinned or "
                "pass explicit shard_rules.", len(stage_ids) - 1)
            return out
    for pname, spec in param_specs.items():
        if any((pat[1:] == pname if pat.startswith("=") else pat in pname)
               for pat in out):
            continue  # a rule already names this parameter — it wins,
            # including an explicit P() asking for replication
        owner = pname[1:].rsplit(".", 1)[0] if pname.startswith("_") else None
        shape = getattr(spec, "shape", None)
        if owner in pinned and shape and shape[-1] % n_model == 0:
            out["=" + pname] = P(
                *([None] * (len(shape) - 1) + [MODEL_AXIS]))
    return out


def shard_opt_state(opt_state, mesh: Mesh,
                    rules: Optional[Dict[str, P]] = None):
    """Shard any optimizer-state pytree: entries of per-parameter dicts
    ("slots", "avg", or any future key whose value is {param_name: ...})
    follow their owning parameter's rule; everything else replicates.

    Rule keys use ``rule_for``'s matching contract: a key starting with
    ``=`` matches the parameter name EXACTLY (the auto-added per-parameter
    rules use this so a rule for ``_emb.w0`` can never capture
    ``_user_emb.w0``); any other key matches as a substring of the name.

    A dimension a rule would shard that is NOT divisible by the mesh axis
    size keeps that leaf replicated — loudly: the warning names the
    parameter, the dim, and the axis. Since r17 the fallback decision
    lives in ``parallel/layout.py:SpecLayout.slot_sharding`` (one
    ``axis_divides`` predicate, shared with graftlint PT502's
    dividing-axis gate, so the placement and the audit always report
    the same decision); this is a compatibility wrapper over
    ``SpecLayout.place_opt_state``."""
    from paddle_tpu.parallel.layout import SpecLayout
    return SpecLayout(mesh, rules=rules).place_opt_state(opt_state)
