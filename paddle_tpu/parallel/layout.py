"""SpecLayout — the canonical 4D sharding plane (data × fsdp × tp × pipe).

Every parallelism axis in this repo was added one PR at a time, and their
pairwise composition used to be hand-negotiated: ZeRO-1 excluded the
pipeline's stage-stacked keys via ``P(pipe)`` rules it had to know about,
``shard_opt_state`` and graftlint PT502 each re-derived the
non-divisible-dim replicated fallback, and init / the train step /
checkpoint load each called their own chain of
``effective_rules``/``device_attr_rules``/``rule_for``. This module is
the single placement layer the TensorFlow paper (PAPERS.md) argues for
and modern TPU stacks spell as one named-axis PartitionSpec table
(SNIPPETS.md [2]): ONE ``SpecLayout`` object owns the canonical per-role
spec map, and init, the train step, ZeRO-1/FSDP, the pipeline,
checkpointing, and serving reshard all *derive* their shardings from it.

Mesh axes and what each one means:

==========  =============================================================
axis        role
==========  =============================================================
``data``    batch rows; gradients all-reduce over it (pure DP).
``fsdp``    batch rows AND flat-packed parameter/optimizer state: the
            batch is split over ``data × fsdp`` jointly, while eligible
            parameters live packed ``(N, chunk)`` sharded 1/N over this
            axis with gather-on-use (``optim/zero1.py:FsdpUpdater``).
``model``   tensor parallelism (the ``tp`` plane): row/column-sharded
            tables and projections via per-name rules.
``seq``     sequence/context parallelism (ring/ulysses attention).
``pipe``    GPipe stages: stage-stacked body state, one stage per slot.
``dcn``     cross-slice data parallelism (leading, multi-slice meshes).
==========  =============================================================

Roles the layout answers for (the derivation map each subsystem uses is
tabulated in ``docs/spec_layout.md``):

- **batch**    — ``batch_spec``/``batch_sharding``: dim 0 over
  ``(dcn, data, fsdp)``, the rest replicated.
- **param**    — ``param_spec``/``param_sharding``/``param_shardings``:
  the canonical rule table (user rules + the sparse-table row-sharding
  default + ``--parallel_nn`` device-attr hints + any pipeline pins),
  resolved with ``rule_for``'s exact-before-substring precedence.
- **slot**     — ``slot_sharding``: optimizer slots follow their owning
  parameter's spec, trimmed to the leaf's rank, with THE
  non-divisible-dim replicated fallback (``fits``/``axis_divides`` —
  the same decision graftlint PT502 gates on, so the audit and the
  placement can never disagree about when replication is legitimate).
- **packed**   — ``packed_sharding``: the flat ``(N, chunk)`` layout
  ZeRO-1/FSDP state uses, over the partition axes the updater declares.
- **stacked**  — pipeline pins installed with ``pin``/``unpin``: the
  stage-stacked keys become ordinary exact-match rules in the one
  table, so ZeRO-1/FSDP eligibility, ``shard_opt_state`` and PT505
  hygiene all see them through the same query.

Construction is cheap (no device ops); placement methods
(``place_params``/``place_opt_state``) perform the device_puts.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Tuple

from jax.sharding import NamedSharding, PartitionSpec as P


def axis_divides(dim: int, axis_size: int) -> bool:
    """THE dividing-axis decision, shared by the slot-placement fallback
    (``SpecLayout.slot_sharding``) and graftlint PT502's gate
    (``analysis/shard_audit.py:replication_findings``): a mesh axis of
    size ``s`` divides a dim ``d`` when ``s > 1``, ``d >= s`` and
    ``d % s == 0``. A leaf none of whose ruled dims pass this test is
    LEGITIMATELY replicated (one warning, not a PT502 finding) — one
    predicate, consulted from both sides, so the placement and the
    audit report the same decision."""
    return axis_size > 1 and dim >= axis_size and dim % axis_size == 0


class SpecLayout:
    """The canonical per-role PartitionSpec map for one mesh.

    ``param_specs`` may be a ``{name: ParamSpec}`` dict (enables the
    sparse-table default and device-attr hints) or any iterable of
    names (rules only). ``graph`` supplies the per-layer ``device``
    attrs for the ``--parallel_nn`` shard-hint form."""

    def __init__(self, mesh, param_specs=None, graph=None,
                 rules: Optional[Dict[str, P]] = None):
        from paddle_tpu.parallel import mesh as mesh_lib
        self.mesh = mesh
        self.param_specs = (param_specs
                            if isinstance(param_specs, dict) else None)
        # the canonical rule table: user rules + the sparse row-sharding
        # default + per-layer device placement mapped to model-axis
        # sharding — built ONCE here instead of per-call-site
        rules = mesh_lib.effective_rules(param_specs or {}, mesh, rules)
        rules = mesh_lib.device_attr_rules(graph, self.param_specs, mesh,
                                           rules)
        self.rules: Dict[str, P] = dict(rules)

    # ------------------------------------------------------------- axes
    def axis_size(self, axis: str) -> int:
        return int(dict(self.mesh.shape).get(axis, 1))

    @property
    def data(self) -> int:
        from paddle_tpu.parallel.mesh import DATA_AXIS
        return self.axis_size(DATA_AXIS)

    @property
    def fsdp(self) -> int:
        from paddle_tpu.parallel.mesh import FSDP_AXIS
        return self.axis_size(FSDP_AXIS)

    @property
    def tp(self) -> int:
        from paddle_tpu.parallel.mesh import MODEL_AXIS
        return self.axis_size(MODEL_AXIS)

    @property
    def seq(self) -> int:
        from paddle_tpu.parallel.mesh import SEQ_AXIS
        return self.axis_size(SEQ_AXIS)

    @property
    def pipe(self) -> int:
        from paddle_tpu.parallel.mesh import PIPE_AXIS
        return self.axis_size(PIPE_AXIS)

    # ------------------------------------------------------------ batch
    def batch_axes(self) -> tuple:
        from paddle_tpu.parallel import mesh as mesh_lib
        return mesh_lib.batch_axes(self.mesh)

    def batch_spec(self, ndim: int = 1) -> P:
        # delegates: mesh.batch_sharding is the one construction site
        # (data/prefetch device_put and shard_batch ride it too)
        return self.batch_sharding(ndim).spec

    def batch_sharding(self, ndim: int = 1) -> NamedSharding:
        from paddle_tpu.parallel import mesh as mesh_lib
        return mesh_lib.batch_sharding(self.mesh, ndim)

    # ----------------------------------------------------------- params
    def rule_key(self, name: str) -> Optional[str]:
        from paddle_tpu.parallel.mesh import rule_key_for
        return rule_key_for(name, self.rules)

    def param_spec(self, name: str) -> P:
        from paddle_tpu.parallel.mesh import rule_for
        return rule_for(name, self.rules)

    def is_replicated(self, name: str) -> bool:
        return self.param_spec(name) == P()

    def param_sharding(self, name: str) -> NamedSharding:
        return NamedSharding(self.mesh, self.param_spec(name))

    def param_shardings(self, names: Iterable[str]
                        ) -> Dict[str, NamedSharding]:
        """NamedSharding per parameter name — the INIT derivation: jit
        out_shardings so big sharded tables are created in place."""
        return {n: self.param_sharding(n) for n in names}

    def place_params(self, params: Dict[str, Any]) -> Dict[str, Any]:
        import jax
        return {n: jax.device_put(p, self.param_sharding(n))
                for n, p in params.items()}

    # ------------------------------------------------------------ slots
    def fits(self, shape, spec: P) -> Optional[str]:
        """Does ``spec`` place a leaf of ``shape`` without a
        non-dividing ruled dim? None when it fits; otherwise a reason
        string naming the first dim/axis that fails ``axis_divides``
        (the caller replicates the leaf and warns with it)."""
        for i, entry in enumerate(spec):
            if entry is None:
                continue
            names = entry if isinstance(entry, tuple) else (entry,)
            sz = 1
            for a in names:
                sz *= self.axis_size(a)
            if sz > 1 and not axis_divides(int(shape[i]), sz):
                return (f"dim {i} of size {shape[i]} not divisible by "
                        f"mesh axis {entry!r} (size {sz})")
        return None

    def slot_sharding(self, name: str, leaf) -> NamedSharding:
        """The sharding an optimizer-slot leaf of ``name`` takes: the
        owning parameter's spec trimmed to the leaf's rank (slots may
        have fewer dims, e.g. per-row timestamps [V] vs the table
        [V, D]); a spec that doesn't fit falls back to replicated,
        LOUDLY — one warning, one decision path (graftlint PT502 gates
        on the same ``axis_divides`` predicate)."""
        from paddle_tpu.utils.log import logger
        spec = P(*self.param_spec(name)[:leaf.ndim])
        why = self.fits(leaf.shape, spec)
        if why is not None:
            logger.warning(
                "SpecLayout: slot of %r: %s — keeping this leaf "
                "replicated (every device pays its full bytes); pad "
                "the parameter or drop the rule", name, why)
            return NamedSharding(self.mesh, P())
        return NamedSharding(self.mesh, spec)

    def place_opt_state(self, opt_state: Dict[str, Any]) -> Dict[str, Any]:
        """Shard an optimizer-state pytree: entries of per-parameter
        dicts (``slots``, ``avg``, any ``{param_name: ...}`` value)
        follow their owning parameter's rule; everything else
        replicates."""
        import jax
        rep = NamedSharding(self.mesh, P())
        out = {}
        for key, val in opt_state.items():
            if isinstance(val, dict):
                out[key] = {
                    name: jax.tree_util.tree_map(
                        lambda x, n=name: jax.device_put(
                            x, self.slot_sharding(n, x)), sub)
                    for name, sub in val.items()}
            else:
                out[key] = jax.device_put(val, rep)
        return out

    # ----------------------------------------------------------- packed
    def packed_axes(self, fsdp: bool = False) -> tuple:
        """The partition axes the flat-packed ``(N, chunk)`` state uses:
        the batch axes for ZeRO-1 (slots follow the gradient
        partition), the fsdp axis alone for FSDP (parameters must stay
        replicated over plain data so the batch axes can keep carrying
        independent rows)."""
        from paddle_tpu.parallel.mesh import FSDP_AXIS
        if fsdp:
            return (FSDP_AXIS,)
        return self.batch_axes()

    def packed_spec(self, fsdp: bool = False) -> P:
        return P(self.packed_axes(fsdp))

    def packed_sharding(self, fsdp: bool = False) -> NamedSharding:
        return NamedSharding(self.mesh, self.packed_spec(fsdp))

    # ------------------------------------------------------------- pins
    def pin(self, rules: Dict[str, P]) -> None:
        """Install exact-match pins (the pipeline's stage-stacked keys)
        into the canonical table — they become ordinary rules every
        derivation (slots, ZeRO-1/FSDP eligibility, PT505 hygiene)
        sees through the same query."""
        self.rules.update(rules)

    def unpin(self, keys: Iterable[str]) -> None:
        for k in keys:
            self.rules.pop(k, None)

    # --------------------------------------------------------- prefetch
    def prefetch_schedule(self, names: Iterable[str],
                          graph=None) -> List[str]:
        """Order FSDP-planned parameter names by FIRST CONSUMER: the
        position (in the network's topological layer order) of the layer
        that owns each parameter. This is the double-buffer schedule the
        overlapped gather path walks (``optim/zero1.py:
        FsdpUpdater.full_params``; ``docs/spec_layout.md`` overlap
        section) — gather k+1 is legal to issue exactly when its
        consumer sits after gather k's consumer, so consumption order IS
        the prefetch order. Without a graph (or for names the graph
        doesn't own) the given order is kept: ``init_params`` iterates
        ``sorted(param_specs)``, a deterministic (if consumption-blind)
        fallback. Stable sort, so ties keep the caller's order."""
        names = list(names)
        if graph is None:
            return names
        rank: Dict[str, int] = {}
        order = list(getattr(graph, "order", ()))
        for idx, layer in enumerate(order):
            for pname in getattr(graph, "_layer_params", {}).get(
                    layer, {}).values():
                if pname not in rank:
                    rank[pname] = idx
        return sorted(names, key=lambda n: rank.get(n, len(order)))

    # ------------------------------------------------- FSDP eligibility
    def fsdp_eligible(self, name: str, spec=None, optimizer=None) -> bool:
        """Is ``name`` in the FSDP/ZeRO flat-packed plan? Excluded:
        static parameters (no slots), sparse lazy tables (row-structured
        bookkeeping), and anything the canonical table already places
        (model-sharded tables, pipeline stage-stacked keys) — their
        state follows that rule instead. The ONE eligibility question
        ZeRO-1 and FSDP both ask (``optim/zero1.py``)."""
        if spec is not None and getattr(spec, "is_static", False):
            return False
        if optimizer is not None and optimizer._is_sparse(spec):
            return False
        return self.is_replicated(name)

    # ------------------------------------------------------------ table
    def describe(self, names: Iterable[str] = ()) -> List[Tuple[str, str,
                                                                str]]:
        """(name, role, spec) rows — the human-readable derivation
        table ``docs/spec_layout.md`` documents; handy in a REPL."""
        rows = [("<batch>", "batch", str(self.batch_spec(2)))]
        for n in names:
            spec = self.param_spec(n)
            role = "param"
            key = self.rule_key(n)
            if key is not None and key.startswith("=") and \
                    any(a == "pipe" for a in _flat_axes(spec)):
                role = "stacked"
            elif not self.is_replicated(n):
                role = "tp/ruled"
            rows.append((n, role, str(spec)))
        return rows


def _flat_axes(spec: P):
    out = []
    for entry in spec:
        if entry is None:
            continue
        out.extend(entry if isinstance(entry, tuple) else (entry,))
    return out
