"""Pipeline parallelism: GPipe-style microbatched stage execution.

The 2017 reference has no pipeline parallelism (SURVEY §2: its model
parallelism is per-layer device placement with task-queue threads); this
module is the TPU-native capability-add completing the tp/pp/dp/sp/ep
set. The classic SPMD formulation (public GPipe/collective-permute
pattern):

- the network is a stack of S identical-shape stages; device i of the
  pipe axis holds stage i's parameters (stacked leading axis, sharded);
- a batch splits into M microbatches; over ``S + M - 1`` ticks each
  device computes its stage for the microbatch in flight and passes the
  activation to the next device with ``lax.ppermute`` — compute on tick
  t overlaps the transfer for tick t+1 (XLA pipelines the permute);
- the bubble is the usual ``(S-1)/(S+M-1)`` fraction: more microbatches,
  less bubble.

``pipeline_apply`` runs inside ``shard_map`` over the pipe axis; the
whole schedule is one ``lax.scan``, so XLA sees a single fused loop.
``stack_stage_params``/``shard_pipeline_params`` build the stacked
layout. Forward parity with sequential stage application and gradient
flow are pinned in ``tests/test_pipeline.py``.
"""

from __future__ import annotations

from typing import Callable, Dict, List

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def stack_stage_params(stage_params: List[Dict[str, jnp.ndarray]]
                       ) -> Dict[str, jnp.ndarray]:
    """[{name: value} per stage] -> {name: stacked [S, ...]}."""
    out = {}
    for k in stage_params[0]:
        out[k] = jnp.stack([sp[k] for sp in stage_params])
    return out


def shard_pipeline_params(stacked, mesh: Mesh, axis: str):
    """Stage-major placement: leading (stage) dim over the pipe axis."""
    return {k: jax.device_put(v, NamedSharding(mesh, P(axis)))
            for k, v in stacked.items()}


def sequential_apply(stage_fn: Callable, stacked, x):
    """Single-device reference: stages applied in order (no pipeline)."""
    S = next(iter(stacked.values())).shape[0]
    h = x
    for s in range(S):
        h = stage_fn({k: v[s] for k, v in stacked.items()}, h)
    return h


def make_pipeline(mesh: Mesh, axis: str, stage_fn: Callable,
                  n_microbatches: int):
    """Returns ``fn(stacked_sharded_params, x) -> y`` running the GPipe
    schedule over ``axis``. ``x`` is the full [B, ...] batch (replicated
    over the pipe axis; shard it over the data axis as usual);
    B % n_microbatches == 0."""
    S = mesh.shape[axis]
    M = n_microbatches

    def local(params, x):
        # params: this device's stage params, leading dim 1 -> squeeze
        p_mine = {k: v[0] for k, v in params.items()}
        idx = lax.axis_index(axis)
        B = x.shape[0]
        mb = x.reshape(M, B // M, *x.shape[1:])
        n_ticks = S + M - 1

        perm_fwd = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            inflight, outputs = carry
            # stage 0 injects microbatch t (when t < M); others take the
            # activation handed over from the previous stage
            feed = jnp.where(t < M, 1, 0)
            mb_t = mb[jnp.clip(t, 0, M - 1)]
            h_in = jnp.where((idx == 0) & (feed == 1), mb_t, inflight)
            h_out = stage_fn(p_mine, h_in)
            # the LAST stage's output for microbatch m lands at tick
            # m + S - 1: record it
            m_done = t - (S - 1)
            is_done = (idx == S - 1) & (m_done >= 0) & (m_done < M)
            outputs = lax.cond(
                is_done,
                lambda o: lax.dynamic_update_index_in_dim(
                    o, h_out, jnp.clip(m_done, 0, M - 1), axis=0),
                lambda o: o, outputs)
            # hand the activation to the next stage for the next tick
            h_next = lax.ppermute(h_out, axis, perm_fwd)
            return (h_next, outputs), None

        inflight0 = jnp.zeros_like(mb[0])
        outputs0 = jnp.zeros_like(mb)
        (_, outputs), _ = lax.scan(
            tick, (inflight0, outputs0), jnp.arange(n_ticks))
        # only the last stage holds real outputs; broadcast them so the
        # result is replicated over the pipe axis (psum of a one-hot)
        mask = (idx == S - 1).astype(outputs.dtype)
        outputs = lax.psum(outputs * mask, axis)
        return outputs.reshape(B, *outputs.shape[2:])

    from paddle_tpu.parallel.mesh import shard_map_compat
    fn = shard_map_compat(
        local, mesh=mesh,
        # pytree-prefix specs: every stacked param shards stage-major
        in_specs=(P(axis), P()),
        out_specs=P(), check_vma=False)

    return jax.jit(fn)


# ------------------------------------------------ config-driven stages
def stages_from_device_attrs(graph):
    """Partition a graph's layers into pipeline stages by their per-layer
    ``device`` attr — the reference's layer-placement spelling
    (``ParallelNeuralNetwork.h:23-62`` pins layers to devices via the
    config's ``device`` field) reinterpreted as GPipe stage ids.

    Rules: data layers are stageless (fed to stage 0); every other layer
    needs ``device >= 0``; stage ids must be contiguous from 0 and
    non-decreasing along the topological order (a pipeline is a chain).
    Returns the list of per-stage layer-name lists."""
    order = [n for n in graph.topo_order()
             if graph.layers[n].type != "data"]
    stages: list = []
    last = -1
    for name in order:
        ldef = graph.layers[name]
        dev = int(getattr(ldef, "attrs", {}).get("device", -1))
        if dev < 0:
            raise ValueError(
                f"pipeline-from-device-attrs: layer {name!r} has no "
                "device attr; every non-data layer needs a stage id")
        if dev < last:
            raise ValueError(
                f"layer {name!r} (device {dev}) appears after stage "
                f"{last}: stages must be contiguous along the topo order")
        if dev > last:
            if dev != last + 1:
                raise ValueError(
                    f"stage ids must be contiguous: jumped {last}->{dev}")
            stages.append([])
            last = dev
        stages[dev].append(name)
    return stages


def make_pipeline_from_device_attrs(graph, params, mesh: Mesh, axis: str,
                                    n_microbatches: int, full_net=None):
    """Config-reachable GPipe: build the pipelined forward of a graph
    whose per-layer ``device`` attrs assign stages (the reference's
    placement spelling; see ``stages_from_device_attrs``).

    Requirements (checked): ``mesh.shape[axis] ==`` number of stages;
    stages are structurally identical (same layer-type/size sequence and
    the same parameter shapes — the repeated-block idiom), each stage is
    a chain consuming the previous stage's single output. Returns
    ``(fn, stacked_sharded_params)`` with ``fn(stacked, x) -> y``, plus
    the single-device ``sequential_apply`` parity path via the same
    ``stage_fn`` closure (``fn.stage_fn``, ``fn.stacked``). Pass the
    already-built ``full_net`` (a ``Network(graph)``) to skip rebuilding
    shape inference just for the param-name mapping.

    The same per-layer ``device`` field also serves the trainer's
    model-axis shard hint (``parallel/mesh.py:device_attr_rules``); the
    rule there detects the pipeline spelling (EVERY non-data layer
    staged contiguously from 0) and stands down, so a config written for
    this entry point is not silently model-sharded by the trainer."""
    from paddle_tpu.config.model_config import ModelDef
    from paddle_tpu.core.network import Network
    from paddle_tpu.core.argument import Argument

    stages = stages_from_device_attrs(graph)
    S = len(stages)
    if mesh.shape[axis] != S:
        raise ValueError(f"{S} stages need mesh axis {axis!r} of size "
                         f"{S}, got {mesh.shape[axis]}")
    sigs = [[(graph.layers[n].type, graph.layers[n].size)
             for n in st] for st in stages]
    if any(sig != sigs[0] for sig in sigs[1:]):
        raise ValueError(
            "pipeline stages must be structurally identical (repeated-"
            f"block idiom); got signatures {sigs}")
    # chain topology holds for EVERY stage, not just the stage-0 template
    # (an identically-signed later stage with different fan-in — e.g. a
    # 2-input addto — would otherwise silently execute with stage-0's
    # wiring, ADVICE r05 #2)
    for s, st in enumerate(stages):
        for j, n in enumerate(st):
            names = graph.layers[n].input_names()
            if len(names) != 1:
                raise ValueError(
                    f"stage {s} layer {n!r} must be a chain (single "
                    f"input); it has inputs {names}")
            want = (st[j - 1] if j > 0
                    else stages[s - 1][-1] if s > 0 else None)
            if want is not None and names[0] != want:
                raise ValueError(
                    f"stage {s} layer {n!r} consumes {names[0]!r}, but a "
                    f"pipeline chain requires its predecessor {want!r}")

    # stage-0 template sub-graph: one data layer feeding the chain
    first = graph.layers[stages[0][0]]
    in_name = first.input_names()[0]
    import dataclasses as _dc
    sub = ModelDef()
    in_size = graph.layers[in_name].size if in_name in graph.layers else None
    from paddle_tpu.config.model_config import LayerDef, Input
    sub.add(LayerDef(name="__pipe_in__", type="data", size=in_size))
    prev = "__pipe_in__"
    for n in stages[0]:
        ldef = graph.layers[n]  # fan-in validated for all stages above
        # rewire to the chain predecessor, KEEPING the Input's extra /
        # param_attr (conv filter specs etc. live there)
        sub.add(_dc.replace(
            ldef, inputs=[_dc.replace(ldef.inputs[0], layer_name=prev)]))
        prev = n
    net = Network(sub, outputs=[stages[0][-1]])

    # positional param mapping: stage s's params in stage-0 name space
    full = full_net if full_net is not None else Network(graph)
    per_stage = []
    for st in stages:
        sp = {}
        for tmpl_layer, layer in zip(stages[0], st):
            for suffix, pname in full._layer_params[layer].items():
                tmpl_pname = full._layer_params[tmpl_layer][suffix]
                sp[tmpl_pname] = params[pname]
        per_stage.append(sp)
    shapes = [{k: v.shape for k, v in sp.items()} for sp in per_stage]
    if any(s != shapes[0] for s in shapes[1:]):
        raise ValueError(f"stage parameter shapes differ: {shapes}")
    stacked = stack_stage_params(per_stage)

    def stage_fn(sp, x):
        out = net.apply(sp, {"__pipe_in__": Argument(value=x)},
                        train=False)
        return out[stages[0][-1]].value

    fn = make_pipeline(mesh, axis, stage_fn, n_microbatches)
    fn = _attach(fn, stage_fn, shard_pipeline_params(stacked, mesh, axis))
    return fn, fn.stacked


def _attach(fn, stage_fn, stacked):
    class _Pipe:
        def __init__(self):
            self.stage_fn = stage_fn
            self.stacked = stacked

        def __call__(self, params, x):
            return fn(params, x)

    return _Pipe()
