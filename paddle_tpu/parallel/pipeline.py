"""Pipeline parallelism: GPipe-style microbatched stage execution.

The 2017 reference has no pipeline parallelism (SURVEY §2: its model
parallelism is per-layer device placement with task-queue threads); this
module is the TPU-native capability-add completing the tp/pp/dp/sp/ep
set. The classic SPMD formulation (public GPipe/collective-permute
pattern):

- the network is a stack of S identical-shape stages; device i of the
  pipe axis holds stage i's parameters (stacked leading axis, sharded);
- a batch splits into M microbatches; over ``S + M - 1`` ticks each
  device computes its stage for the microbatch in flight and passes the
  activation to the next device with ``lax.ppermute`` — compute on tick
  t overlaps the transfer for tick t+1 (XLA pipelines the permute);
- the bubble is the usual ``(S-1)/(S+M-1)`` fraction: more microbatches,
  less bubble.

``pipeline_apply`` runs inside ``shard_map`` over the pipe axis; the
whole schedule is one ``lax.scan``, so XLA sees a single fused loop.
``stack_stage_params``/``shard_pipeline_params`` build the stacked
layout. Forward parity with sequential stage application and gradient
flow are pinned in ``tests/test_pipeline.py``.
"""

from __future__ import annotations

from typing import Callable, Dict, List

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def stack_stage_params(stage_params: List[Dict[str, jnp.ndarray]]
                       ) -> Dict[str, jnp.ndarray]:
    """[{name: value} per stage] -> {name: stacked [S, ...]}."""
    out = {}
    for k in stage_params[0]:
        out[k] = jnp.stack([sp[k] for sp in stage_params])
    return out


def shard_pipeline_params(stacked, mesh: Mesh, axis: str):
    """Stage-major placement: leading (stage) dim over the pipe axis."""
    return {k: jax.device_put(v, NamedSharding(mesh, P(axis)))
            for k, v in stacked.items()}


def sequential_apply(stage_fn: Callable, stacked, x):
    """Single-device reference: stages applied in order (no pipeline)."""
    S = next(iter(stacked.values())).shape[0]
    h = x
    for s in range(S):
        h = stage_fn({k: v[s] for k, v in stacked.items()}, h)
    return h


def make_pipeline(mesh: Mesh, axis: str, stage_fn: Callable,
                  n_microbatches: int):
    """Returns ``fn(stacked_sharded_params, x) -> y`` running the GPipe
    schedule over ``axis``. ``x`` is the full [B, ...] batch (replicated
    over the pipe axis; shard it over the data axis as usual);
    B % n_microbatches == 0."""
    S = mesh.shape[axis]
    M = n_microbatches

    def local(params, x):
        # params: this device's stage params, leading dim 1 -> squeeze
        p_mine = {k: v[0] for k, v in params.items()}
        idx = lax.axis_index(axis)
        B = x.shape[0]
        mb = x.reshape(M, B // M, *x.shape[1:])
        n_ticks = S + M - 1

        perm_fwd = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            inflight, outputs = carry
            # stage 0 injects microbatch t (when t < M); others take the
            # activation handed over from the previous stage
            feed = jnp.where(t < M, 1, 0)
            mb_t = mb[jnp.clip(t, 0, M - 1)]
            h_in = jnp.where((idx == 0) & (feed == 1), mb_t, inflight)
            h_out = stage_fn(p_mine, h_in)
            # the LAST stage's output for microbatch m lands at tick
            # m + S - 1: record it
            m_done = t - (S - 1)
            is_done = (idx == S - 1) & (m_done >= 0) & (m_done < M)
            outputs = lax.cond(
                is_done,
                lambda o: lax.dynamic_update_index_in_dim(
                    o, h_out, jnp.clip(m_done, 0, M - 1), axis=0),
                lambda o: o, outputs)
            # hand the activation to the next stage for the next tick
            h_next = lax.ppermute(h_out, axis, perm_fwd)
            return (h_next, outputs), None

        inflight0 = jnp.zeros_like(mb[0])
        outputs0 = jnp.zeros_like(mb)
        (_, outputs), _ = lax.scan(
            tick, (inflight0, outputs0), jnp.arange(n_ticks))
        # only the last stage holds real outputs; broadcast them so the
        # result is replicated over the pipe axis (psum of a one-hot)
        mask = (idx == S - 1).astype(outputs.dtype)
        outputs = lax.psum(outputs * mask, axis)
        return outputs.reshape(B, *outputs.shape[2:])

    from paddle_tpu.parallel.mesh import shard_map_compat
    fn = shard_map_compat(
        local, mesh=mesh,
        # pytree-prefix specs: every stacked param shards stage-major
        in_specs=(P(axis), P()),
        out_specs=P(), check_vma=False)

    return jax.jit(fn)


# ------------------------------------------------ config-driven stages
def stages_from_device_attrs(graph):
    """Partition a graph's layers into pipeline stages by their per-layer
    ``device`` attr — the reference's layer-placement spelling
    (``ParallelNeuralNetwork.h:23-62`` pins layers to devices via the
    config's ``device`` field) reinterpreted as GPipe stage ids.

    Rules: data layers are stageless (fed to stage 0); every other layer
    needs ``device >= 0``; stage ids must be contiguous from 0 and
    non-decreasing along the topological order (a pipeline is a chain).
    Returns the list of per-stage layer-name lists."""
    order = [n for n in graph.topo_order()
             if graph.layers[n].type != "data"]
    stages: list = []
    last = -1
    for name in order:
        ldef = graph.layers[name]
        dev = int(getattr(ldef, "attrs", {}).get("device", -1))
        if dev < 0:
            raise ValueError(
                f"pipeline-from-device-attrs: layer {name!r} has no "
                "device attr; every non-data layer needs a stage id")
        if dev < last:
            raise ValueError(
                f"layer {name!r} (device {dev}) appears after stage "
                f"{last}: stages must be contiguous along the topo order")
        if dev > last:
            if dev != last + 1:
                raise ValueError(
                    f"stage ids must be contiguous: jumped {last}->{dev}")
            stages.append([])
            last = dev
        stages[dev].append(name)
    return stages


def make_pipeline_from_device_attrs(graph, params, mesh: Mesh, axis: str,
                                    n_microbatches: int, full_net=None):
    """Config-reachable GPipe: build the pipelined forward of a graph
    whose per-layer ``device`` attrs assign stages (the reference's
    placement spelling; see ``stages_from_device_attrs``).

    Requirements (checked): ``mesh.shape[axis] ==`` number of stages;
    stages are structurally identical (same layer-type/size sequence and
    the same parameter shapes — the repeated-block idiom), each stage is
    a chain consuming the previous stage's single output. Returns
    ``(fn, stacked_sharded_params)`` with ``fn(stacked, x) -> y``, plus
    the single-device ``sequential_apply`` parity path via the same
    ``stage_fn`` closure (``fn.stage_fn``, ``fn.stacked``). Pass the
    already-built ``full_net`` (a ``Network(graph)``) to skip rebuilding
    shape inference just for the param-name mapping.

    The same per-layer ``device`` field also serves the trainer's
    model-axis shard hint (``parallel/mesh.py:device_attr_rules``); the
    rule there detects the pipeline spelling (EVERY non-data layer
    staged contiguously from 0) and stands down, so a config written for
    this entry point is not silently model-sharded by the trainer."""
    from paddle_tpu.config.model_config import ModelDef
    from paddle_tpu.core.network import Network
    from paddle_tpu.core.argument import Argument

    stages = stages_from_device_attrs(graph)
    S = len(stages)
    if mesh.shape[axis] != S:
        raise ValueError(f"{S} stages need mesh axis {axis!r} of size "
                         f"{S}, got {mesh.shape[axis]}")
    sigs = [[(graph.layers[n].type, graph.layers[n].size)
             for n in st] for st in stages]
    if any(sig != sigs[0] for sig in sigs[1:]):
        raise ValueError(
            "pipeline stages must be structurally identical (repeated-"
            f"block idiom); got signatures {sigs}")
    # chain topology holds for EVERY stage, not just the stage-0 template
    # (an identically-signed later stage with different fan-in — e.g. a
    # 2-input addto — would otherwise silently execute with stage-0's
    # wiring, ADVICE r05 #2)
    for s, st in enumerate(stages):
        for j, n in enumerate(st):
            names = graph.layers[n].input_names()
            if len(names) != 1:
                raise ValueError(
                    f"stage {s} layer {n!r} must be a chain (single "
                    f"input); it has inputs {names}")
            want = (st[j - 1] if j > 0
                    else stages[s - 1][-1] if s > 0 else None)
            if want is not None and names[0] != want:
                raise ValueError(
                    f"stage {s} layer {n!r} consumes {names[0]!r}, but a "
                    f"pipeline chain requires its predecessor {want!r}")

    # stage-0 template sub-graph: one data layer feeding the chain
    first = graph.layers[stages[0][0]]
    in_name = first.input_names()[0]
    import dataclasses as _dc
    sub = ModelDef()
    in_size = graph.layers[in_name].size if in_name in graph.layers else None
    from paddle_tpu.config.model_config import LayerDef, Input
    sub.add(LayerDef(name="__pipe_in__", type="data", size=in_size))
    prev = "__pipe_in__"
    for n in stages[0]:
        ldef = graph.layers[n]  # fan-in validated for all stages above
        # rewire to the chain predecessor, KEEPING the Input's extra /
        # param_attr (conv filter specs etc. live there)
        sub.add(_dc.replace(
            ldef, inputs=[_dc.replace(ldef.inputs[0], layer_name=prev)]))
        prev = n
    net = Network(sub, outputs=[stages[0][-1]])

    # positional param mapping: stage s's params in stage-0 name space
    full = full_net if full_net is not None else Network(graph)
    per_stage = []
    for st in stages:
        sp = {}
        for tmpl_layer, layer in zip(stages[0], st):
            for suffix, pname in full._layer_params[layer].items():
                tmpl_pname = full._layer_params[tmpl_layer][suffix]
                sp[tmpl_pname] = params[pname]
        per_stage.append(sp)
    shapes = [{k: v.shape for k, v in sp.items()} for sp in per_stage]
    if any(s != shapes[0] for s in shapes[1:]):
        raise ValueError(f"stage parameter shapes differ: {shapes}")
    stacked = stack_stage_params(per_stage)

    def stage_fn(sp, x):
        out = net.apply(sp, {"__pipe_in__": Argument(value=x)},
                        train=False)
        return out[stages[0][-1]].value

    fn = make_pipeline(mesh, axis, stage_fn, n_microbatches)
    fn = _attach(fn, stage_fn, shard_pipeline_params(stacked, mesh, axis))
    return fn, fn.stacked


def _attach(fn, stage_fn, stacked):
    class _Pipe:
        def __init__(self):
            self.stage_fn = stage_fn
            self.stacked = stacked

        def __call__(self, params, x):
            return fn(params, x)

    return _Pipe()


# ---------------------------------------------------------- training plan
def split_pipeline_graph(graph):
    """Partition a graph into ``(stages, head)`` for *training* through
    the pipeline: the staged body (layers carrying a nonnegative
    ``device`` attr, the reference's ``--parallel_nn`` placement spelling,
    ``ParallelNeuralNetwork.h:23-62``) plus the trailing unstaged head
    (cost layers, evaluator decodes) computed replicated on the body
    output. Unlike :func:`stages_from_device_attrs` (forward-only: every
    non-data layer must be staged), a training config keeps its cost
    layers unstaged — the loss is not part of the repeated block.

    Rules: staged layers form a chain with contiguous stage ids along the
    topological order, consuming only data layers (stage-0 entry) or other
    staged layers; head layers may consume data layers, other head layers,
    and the LAST staged layer only (a head reaching into an intermediate
    stage would need a second activation route the schedule doesn't
    carry). Raises ``ValueError`` with a pinpointed message otherwise —
    the trainer catches it and stands down to the unpipelined step."""
    order = [n for n in graph.topo_order() if graph.layers[n].type != "data"]

    def dev(n):
        return int(getattr(graph.layers[n], "attrs", {}).get("device", -1))

    staged = [n for n in order if dev(n) >= 0]
    if not staged:
        raise ValueError("pipeline: no layer carries a device attr")
    head = [n for n in order if dev(n) < 0]
    staged_set = set(staged)
    last_staged = staged[-1]
    for n in head:
        for src in graph.layers[n].input_names():
            if src in staged_set and src != last_staged:
                raise ValueError(
                    f"pipeline head layer {n!r} consumes intermediate "
                    f"stage output {src!r}; the head may read only the "
                    f"last staged layer ({last_staged!r})")
    for n in staged:
        for src in graph.layers[n].input_names():
            if src not in staged_set and graph.layers[src].type != "data":
                raise ValueError(
                    f"staged layer {n!r} consumes unstaged layer {src!r}: "
                    "every body input must be a data layer or another "
                    "staged layer")
    stages: list = []
    last = -1
    for name in staged:
        d = dev(name)
        if d < last:
            raise ValueError(
                f"layer {name!r} (device {d}) appears after stage {last}: "
                "stages must be non-decreasing along the topo order")
        if d > last:
            if d != last + 1:
                raise ValueError(
                    f"stage ids must be contiguous: jumped {last}->{d}")
            stages.append([])
            last = d
        stages[d].append(name)
    if len(stages) < 2:
        raise ValueError("pipeline needs >= 2 stages")
    # chain topology per stage (single input, exact predecessor)
    for s, st in enumerate(stages):
        for j, n in enumerate(st):
            names = graph.layers[n].input_names()
            if len(names) != 1:
                raise ValueError(
                    f"stage {s} layer {n!r} must be a chain (single "
                    f"input); it has inputs {names}")
            want = (st[j - 1] if j > 0
                    else stages[s - 1][-1] if s > 0 else None)
            if want is not None and names[0] != want:
                raise ValueError(
                    f"stage {s} layer {n!r} consumes {names[0]!r}, but a "
                    f"pipeline chain requires its predecessor {want!r}")
    return stages, head


def _stage_subnet(graph, layer_names, in_name, in_size):
    """Sub-Network for one stage: a ``__pipe_in__`` data stand-in feeding
    the stage's chain (Input extras/param_attrs preserved — conv filter
    specs live there)."""
    import dataclasses as _dc

    from paddle_tpu.config.model_config import LayerDef, ModelDef
    from paddle_tpu.core.network import Network

    sub = ModelDef()
    sub.add(LayerDef(name="__pipe_in__", type="data", size=in_size))
    prev = "__pipe_in__"
    for n in layer_names:
        ldef = graph.layers[n]
        sub.add(_dc.replace(
            ldef, inputs=[_dc.replace(ldef.inputs[0], layer_name=prev)]))
        prev = n
    return Network(sub, outputs=[layer_names[-1]])


def _schedule(mesh: Mesh, axis: str, stage_call, S: int, M: int,
              params_spec, batch_axes=()):
    """The GPipe fill-drain schedule as one shard_map'd ``lax.scan`` over
    ``S + M - 1`` ticks (its ``jax.grad`` is the reverse drain — the
    backward pipeline). ``stage_call(params, idx, h, rng) -> h`` runs this
    device's stage; ``params_spec`` is the shard_map in_spec prefix for
    the params pytree (``P(axis)`` stage-stacked, ``P()`` replicated for
    heterogeneous stages). ``batch_axes`` (the mesh's data axes) shard the
    batch dim of x/y so the pipeline composes with data parallelism: each
    data slot runs the same schedule on its rows."""
    x_spec = P(batch_axes) if batch_axes else P()

    def local(sp, x, rng):
        idx = lax.axis_index(axis)
        B = x.shape[0]
        if B % M:
            raise ValueError(
                f"pipeline microbatches ({M}) must divide the per-device "
                f"batch ({B} rows)")
        mb = x.reshape(M, B // M, *x.shape[1:])
        n_ticks = S + M - 1
        perm_fwd = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            inflight, outputs = carry
            feed = jnp.where(t < M, 1, 0)
            mb_t = mb[jnp.clip(t, 0, M - 1)]
            h_in = jnp.where((idx == 0) & (feed == 1), mb_t, inflight)
            # per-tick rng: without the fold every microbatch would
            # sample the SAME dropout mask (the grad-accum path splits
            # per microbatch for the same reason, trainer.py accum_step)
            r_t = (jax.random.fold_in(rng, t) if rng is not None else None)
            h_out = stage_call(sp, idx, h_in, r_t)
            m_done = t - (S - 1)
            is_done = (idx == S - 1) & (m_done >= 0) & (m_done < M)
            outputs = lax.cond(
                is_done,
                lambda o: lax.dynamic_update_index_in_dim(
                    o, h_out, jnp.clip(m_done, 0, M - 1), axis=0),
                lambda o: o, outputs)
            h_next = lax.ppermute(h_out, axis, perm_fwd)
            return (h_next, outputs), None

        inflight0 = jnp.zeros_like(mb[0])
        outputs0 = jnp.zeros_like(mb)
        (_, outputs), _ = lax.scan(
            tick, (inflight0, outputs0), jnp.arange(n_ticks))
        mask = (idx == S - 1).astype(outputs.dtype)
        outputs = lax.psum(outputs * mask, axis)
        return outputs.reshape(B, *outputs.shape[2:])

    from paddle_tpu.parallel.mesh import shard_map_compat
    return shard_map_compat(
        local, mesh=mesh, in_specs=(params_spec, x_spec, P()),
        out_specs=x_spec, check_vma=False)


class PipelineTrainPlan:
    """Everything the trainer needs to run a device-attr config's body
    through the GPipe schedule inside the jitted train step.

    Identical stages (the repeated-block idiom) take the SPMD fast path:
    the body's parameters restructure to stage-stacked ``[S, ...]`` arrays
    sharded ``P(pipe)`` — each mesh slot permanently holds ONE stage's
    parameters and optimizer slots (1/S of the body state per device), the
    reference's per-device layer ownership made SPMD. Structurally uneven
    splits (different layer counts per stage, uniform boundary width) fall
    back to ``lax.switch`` over per-stage sub-networks with replicated
    parameters — the schedule still pipelines, only the memory win is
    forfeited (documented in docs/pipeline_parallel.md).

    Construction VALIDATES and raises ``ValueError`` on any config the
    schedule cannot honor; the trainer turns that into a warn-and-stand-
    down, never a broken step."""

    def __init__(self, graph, full_net, params, meta, mesh: Mesh,
                 axis: str, n_microbatches=None):
        self.graph, self.mesh, self.axis = graph, mesh, axis
        self.stages, self.head = split_pipeline_graph(graph)
        self.S = len(self.stages)
        if axis not in mesh.axis_names:
            raise ValueError(f"mesh {dict(mesh.shape)} has no {axis!r} axis")
        if mesh.shape[axis] != self.S:
            raise ValueError(
                f"{self.S} stages need mesh axis {axis!r} of size "
                f"{self.S}, got {mesh.shape[axis]}")
        # default M = S: bubble (S-1)/(2S-1) just under one half — a sane
        # floor; raise M (more, smaller microbatches) to shrink it
        self.M = int(n_microbatches) if n_microbatches else self.S
        if self.M < 1:
            raise ValueError("n_microbatches must be >= 1")
        first = graph.layers[self.stages[0][0]]
        self.body_in = first.input_names()[0]
        self.body_out = self.stages[-1][-1]
        if graph.layers[self.body_in].type != "data":
            raise ValueError(
                f"stage 0 must consume a data layer; {self.body_in!r} "
                f"is {graph.layers[self.body_in].type!r}")
        # the handoff buffer has ONE shape: every stage boundary (and the
        # body input) must share the feature width
        widths = [graph.layers[st[-1]].size for st in self.stages]
        in_w = graph.layers[self.body_in].size
        if any(w != widths[0] for w in widths) or in_w != widths[0]:
            raise ValueError(
                f"pipeline stage boundary widths must be uniform and "
                f"equal the body input size; got input {in_w}, stage "
                f"outputs {widths}")
        for st in self.stages:
            for n in st:
                t = graph.layers[n].type
                if t in ("batch_norm", "cudnn_batch_norm",
                         "batch_normalization"):
                    raise ValueError(
                        f"staged layer {n!r} is a batch-stat layer: "
                        "moving-statistic updates cannot thread through "
                        "the pipeline scan")
        # body parameter ownership: per-stage nets + name bookkeeping
        body_pnames = []
        self._stage_pnames = []
        for st in self.stages:
            sp = []
            for layer in st:
                sp.extend(sorted(full_net._layer_params[layer].values()))
            self._stage_pnames.append(sp)
            body_pnames.extend(sp)
        if len(set(body_pnames)) != len(body_pnames):
            raise ValueError(
                "pipeline stages share parameters (explicit param names "
                "across stages); stage-stacked layout cannot hold them")
        self.body_pnames = body_pnames
        head_pnames = {p for layer in self.head
                       for p in full_net._layer_params[layer].values()}
        if head_pnames & set(body_pnames):
            raise ValueError(
                "a parameter is shared between the pipeline body and the "
                "head; split the sharing or unpin the layer")
        sigs = [[(graph.layers[n].type, graph.layers[n].size)
                 for n in st] for st in self.stages]
        self.identical = all(sig == sigs[0] for sig in sigs[1:])
        if self.identical:
            tmpl = self.stages[0]
            self._tmpl_net = _stage_subnet(graph, tmpl, self.body_in, in_w)
            # stacked key = the stage-0 (template) parameter name; maps
            # positionally onto every stage's parameters
            self.stacked_map = {}
            for j, tmpl_layer in enumerate(tmpl):
                for suffix, tmpl_pname in (
                        full_net._layer_params[tmpl_layer].items()):
                    self.stacked_map[tmpl_pname] = [
                        full_net._layer_params[st[j]][suffix]
                        for st in self.stages]
            shapes = [[tuple(params[n].shape) for n in names]
                      for names in self.stacked_map.values()]
            for names, shp in zip(self.stacked_map.values(), shapes):
                if any(s != shp[0] for s in shp[1:]):
                    raise ValueError(
                        f"stage parameter shapes differ for {names}: {shp}")
            # per-stage specs must agree on everything the update reads
            for tmpl_pname, names in self.stacked_map.items():
                s0 = meta[names[0]]
                for n in names[1:]:
                    s = meta[n]
                    if (s.learning_rate, s.is_static, s.l1_rate, s.l2_rate,
                        s.sparsity_ratio) != (
                            s0.learning_rate, s0.is_static, s0.l1_rate,
                            s0.l2_rate, s0.sparsity_ratio):
                        raise ValueError(
                            f"stage parameters {names[0]!r} and {n!r} "
                            "have different update attrs (lr/static/"
                            "l1/l2/sparsity); the stacked update needs "
                            "them uniform")
            self._stage_nets = None
        else:
            self._tmpl_net = None
            self.stacked_map = {}
            prev_out = self.body_in
            self._stage_nets = []
            for st in self.stages:
                self._stage_nets.append(_stage_subnet(
                    graph, st, prev_out, in_w))
                prev_out = st[-1]
        self._fwd_cache = {}

    # ---------------------------------------------------------- forward
    def stacked_keys(self):
        return sorted(self.stacked_map)

    def body_param_names(self):
        """Names the body view of the step's param dict must contain:
        stacked keys on the fast path, the original flat names otherwise."""
        return (self.stacked_keys() if self.identical
                else sorted(self.body_pnames))

    def stacked_spec(self, ndim: int) -> P:
        return P(self.axis, *([None] * (ndim - 1)))

    def fwd(self, M: int, train: bool = True):
        """The shard_map'd schedule for M microbatches (cached — M is a
        static property of the program; a tail batch that needs a smaller
        M compiles its own instance, same as any other shape change).
        Stage rngs fold in the stage index (here) and the tick index
        (inside the schedule) so dropout streams differ per stage AND per
        microbatch (the sampled masks necessarily differ from the
        unpipelined step's — the usual microbatching caveat; parity
        claims hold for deterministic bodies)."""
        key = (M, bool(train))
        if key in self._fwd_cache:
            return self._fwd_cache[key]
        from paddle_tpu.core.argument import Argument
        if self.identical:
            net, out_name = self._tmpl_net, self.stages[0][-1]

            def stage_call(sp, idx, h, rng):
                mine = {k: v[0] for k, v in sp.items()}
                r = (jax.random.fold_in(rng, idx)
                     if rng is not None else None)
                out = net.apply(mine, {"__pipe_in__": Argument(value=h)},
                                train=train, rng=r)
                return out[out_name].value

            params_spec = P(self.axis)
        else:
            nets = self._stage_nets
            outs = [st[-1] for st in self.stages]

            def stage_call(sp, idx, h, rng):
                r = (jax.random.fold_in(rng, idx)
                     if rng is not None else None)

                def branch(s):
                    def run(sp, h):
                        out = nets[s].apply(
                            sp, {"__pipe_in__": Argument(value=h)},
                            train=train, rng=r)
                        return out[outs[s]].value
                    return run

                return lax.switch(idx, [branch(s) for s in range(self.S)],
                                  sp, h)

            params_spec = P()
        from paddle_tpu.parallel import mesh as mesh_lib
        fn = _schedule(self.mesh, self.axis, stage_call, self.S, M,
                       params_spec,
                       batch_axes=mesh_lib.batch_axes(self.mesh))
        self._fwd_cache[key] = fn
        return fn

    # ---------------------------------------------- state restructuring
    def _stacked_sharding(self, ndim: int):
        return NamedSharding(self.mesh, self.stacked_spec(ndim))

    def stack_params(self, params):
        """Flat per-stage params -> stage-stacked params sharded one
        stage per pipe slot. Non-body params pass through."""
        if not self.identical:
            return dict(params)
        body = set(self.body_pnames)
        out = {k: v for k, v in params.items() if k not in body}
        for skey, names in self.stacked_map.items():
            stacked = jnp.stack([params[n] for n in names])
            out[skey] = jax.device_put(
                stacked, self._stacked_sharding(stacked.ndim))
        return out

    def unstack_params(self, params):
        """The checkpoint view: stage-stacked arrays back to the flat
        per-stage names — the on-disk format never depends on whether the
        run was pipelined."""
        if not self.identical:
            return dict(params)
        out = {k: v for k, v in params.items() if k not in self.stacked_map}
        for skey, names in self.stacked_map.items():
            stacked = params[skey]
            for s, n in enumerate(names):
                out[n] = stacked[s]
        return out

    def stack_opt_state(self, state):
        """Per-stage slot dicts -> one stacked slot dict per stacked key
        (leaf-wise stack, sharded like the parameter). Scalars pass
        through; ``avg`` is rejected upstream (enable_pipeline)."""
        if not self.identical:
            return state
        body = set(self.body_pnames)
        slots = {n: s for n, s in state["slots"].items() if n not in body}
        for skey, names in self.stacked_map.items():
            if names[0] not in state["slots"]:
                continue  # static params have no slots
            per = [state["slots"][n] for n in names]
            slots[skey] = {
                slot: jax.device_put(
                    jnp.stack([p[slot] for p in per]),
                    self._stacked_sharding(per[0][slot].ndim + 1))
                for slot in per[0]}
        return {**state, "slots": slots}

    def unstack_opt_state(self, state):
        if not self.identical:
            return state
        slots = {n: s for n, s in state["slots"].items()
                 if n not in self.stacked_map}
        for skey, names in self.stacked_map.items():
            if skey not in state["slots"]:
                continue
            stacked = state["slots"][skey]
            for s, n in enumerate(names):
                slots[n] = {slot: leaf[s] for slot, leaf in stacked.items()}
        return {**state, "slots": slots}

    def stacked_meta(self, meta):
        """meta with per-stage specs replaced by one stacked spec (leading
        S dim; update attrs validated uniform in __init__)."""
        if not self.identical:
            return dict(meta)
        import dataclasses as _dc
        body = set(self.body_pnames)
        out = {k: v for k, v in meta.items() if k not in body}
        for skey, names in self.stacked_map.items():
            spec = meta[names[0]]
            out[skey] = _dc.replace(
                spec, shape=(self.S,) + tuple(spec.shape))
        return out

    def restack_checkpoint(self, params, opt_flat):
        """A restored flat-format checkpoint -> this run's stacked layout
        (host-side numpy; ``SGD.load_state`` places the result)."""
        import numpy as np
        if not self.identical:
            return params, opt_flat
        body = set(self.body_pnames)
        p_out = {k: v for k, v in params.items() if k not in body}
        for skey, names in self.stacked_map.items():
            missing = [n for n in names if n not in params]
            if missing:
                raise ValueError(
                    f"checkpoint lacks pipeline body parameters {missing}")
            p_out[skey] = np.stack([np.asarray(params[n]) for n in names])
        o_out = {}
        grouped: dict = {}
        for key, val in (opt_flat or {}).items():
            parts = key.split("/")
            if len(parts) == 3 and parts[0] == "slots" and parts[1] in body:
                grouped.setdefault(parts[2], {})[parts[1]] = val
            else:
                o_out[key] = val
        for slot, by_name in grouped.items():
            for skey, names in self.stacked_map.items():
                if names[0] in by_name:
                    o_out[f"slots/{skey}/{slot}"] = np.stack(
                        [np.asarray(by_name[n]) for n in names])
        return p_out, o_out

    def build_head_net(self, outputs):
        """Network computing the unstaged head (cost layers, evaluator
        decodes) on the pipeline's output: a data stand-in named exactly
        like the last staged layer (so cost-layer wiring and the metric
        code's ``outputs[...]`` lookups need no rewiring) plus the data
        layers the head consumes, feeding the head layer defs unchanged."""
        import dataclasses as _dc

        from paddle_tpu.config.model_config import LayerDef, ModelDef
        from paddle_tpu.core.network import Network

        g = self.graph
        sub = ModelDef()
        bo = g.layers[self.body_out]
        sub.add(LayerDef(name=self.body_out, type="data", size=bo.size))
        for n in self.head:
            for src in g.layers[n].input_names():
                if (src != self.body_out and src not in sub.layers
                        and g.layers[src].type == "data"):
                    sub.add(_dc.replace(g.layers[src]))
        for n in self.head:
            sub.add(_dc.replace(g.layers[n]))
        return Network(sub, outputs=[n for n in outputs
                                     if n in sub.layers])

    def shard_rules(self):
        """Exact-match rules pinning every stacked key (params AND slots)
        to the stage-major ``P(pipe, ...)`` layout — merged into the
        trainer's rule set so ``shard_opt_state`` keeps slots with their
        stage and the ZeRO-1 planner EXCLUDES the stacked keys from its
        data-axis partitioning (their state is already 1/S per device;
        ZeRO-1 composes by sharding the remaining replicated params —
        the head — over the data axis)."""
        if not self.identical:
            return {}
        return {"=" + skey: self.stacked_spec(2)  # trimmed per-leaf ndim
                for skey in self.stacked_map}
