"""Pipeline parallelism: GPipe-style microbatched stage execution.

The 2017 reference has no pipeline parallelism (SURVEY §2: its model
parallelism is per-layer device placement with task-queue threads); this
module is the TPU-native capability-add completing the tp/pp/dp/sp/ep
set. The classic SPMD formulation (public GPipe/collective-permute
pattern):

- the network is a stack of S identical-shape stages; device i of the
  pipe axis holds stage i's parameters (stacked leading axis, sharded);
- a batch splits into M microbatches; over ``S + M - 1`` ticks each
  device computes its stage for the microbatch in flight and passes the
  activation to the next device with ``lax.ppermute`` — compute on tick
  t overlaps the transfer for tick t+1 (XLA pipelines the permute);
- the bubble is the usual ``(S-1)/(S+M-1)`` fraction: more microbatches,
  less bubble.

``pipeline_apply`` runs inside ``shard_map`` over the pipe axis; the
whole schedule is one ``lax.scan``, so XLA sees a single fused loop.
``stack_stage_params``/``shard_pipeline_params`` build the stacked
layout. Forward parity with sequential stage application and gradient
flow are pinned in ``tests/test_pipeline.py``.
"""

from __future__ import annotations

from typing import Callable, Dict, List

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def stack_stage_params(stage_params: List[Dict[str, jnp.ndarray]]
                       ) -> Dict[str, jnp.ndarray]:
    """[{name: value} per stage] -> {name: stacked [S, ...]}."""
    out = {}
    for k in stage_params[0]:
        out[k] = jnp.stack([sp[k] for sp in stage_params])
    return out


def shard_pipeline_params(stacked, mesh: Mesh, axis: str):
    """Stage-major placement: leading (stage) dim over the pipe axis."""
    return {k: jax.device_put(v, NamedSharding(mesh, P(axis)))
            for k, v in stacked.items()}


def sequential_apply(stage_fn: Callable, stacked, x):
    """Single-device reference: stages applied in order (no pipeline)."""
    S = next(iter(stacked.values())).shape[0]
    h = x
    for s in range(S):
        h = stage_fn({k: v[s] for k, v in stacked.items()}, h)
    return h


def make_pipeline(mesh: Mesh, axis: str, stage_fn: Callable,
                  n_microbatches: int):
    """Returns ``fn(stacked_sharded_params, x) -> y`` running the GPipe
    schedule over ``axis``. ``x`` is the full [B, ...] batch (replicated
    over the pipe axis; shard it over the data axis as usual);
    B % n_microbatches == 0."""
    S = mesh.shape[axis]
    M = n_microbatches

    def local(params, x):
        # params: this device's stage params, leading dim 1 -> squeeze
        p_mine = {k: v[0] for k, v in params.items()}
        idx = lax.axis_index(axis)
        B = x.shape[0]
        mb = x.reshape(M, B // M, *x.shape[1:])
        n_ticks = S + M - 1

        perm_fwd = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            inflight, outputs = carry
            # stage 0 injects microbatch t (when t < M); others take the
            # activation handed over from the previous stage
            feed = jnp.where(t < M, 1, 0)
            mb_t = mb[jnp.clip(t, 0, M - 1)]
            h_in = jnp.where((idx == 0) & (feed == 1), mb_t, inflight)
            h_out = stage_fn(p_mine, h_in)
            # the LAST stage's output for microbatch m lands at tick
            # m + S - 1: record it
            m_done = t - (S - 1)
            is_done = (idx == S - 1) & (m_done >= 0) & (m_done < M)
            outputs = lax.cond(
                is_done,
                lambda o: lax.dynamic_update_index_in_dim(
                    o, h_out, jnp.clip(m_done, 0, M - 1), axis=0),
                lambda o: o, outputs)
            # hand the activation to the next stage for the next tick
            h_next = lax.ppermute(h_out, axis, perm_fwd)
            return (h_next, outputs), None

        inflight0 = jnp.zeros_like(mb[0])
        outputs0 = jnp.zeros_like(mb)
        (_, outputs), _ = lax.scan(
            tick, (inflight0, outputs0), jnp.arange(n_ticks))
        # only the last stage holds real outputs; broadcast them so the
        # result is replicated over the pipe axis (psum of a one-hot)
        mask = (idx == S - 1).astype(outputs.dtype)
        outputs = lax.psum(outputs * mask, axis)
        return outputs.reshape(B, *outputs.shape[2:])

    from jax import shard_map
    fn = shard_map(
        local, mesh=mesh,
        # pytree-prefix specs: every stacked param shards stage-major
        in_specs=(P(axis), P()),
        out_specs=P(), check_vma=False)

    return jax.jit(fn)
