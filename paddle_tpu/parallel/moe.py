"""Expert parallelism: a mixture-of-experts FFN sharded expert-per-device.

The 2017 reference has no MoE (SURVEY §2: no expert parallelism), so —
like ``parallel/ring.py`` — this is a pure capability-add designed
TPU-first. The canonical recipe (the public Switch/GShard pattern):

- router: per-token top-1 expert choice from a learned projection,
  with capacity clipping (static shapes: each expert processes exactly
  ``capacity`` token slots; overflow drops, underflow pads).
- dispatch: each device builds the capacity buffers from its replicated
  token batch and keeps its local experts' slice; the expert FFN runs
  dense (batched [capacity, d] matmuls on the MXU); results return with
  an ``all_gather`` over the expert axis and scatter back weighted by
  the router gate. (With a batch additionally sharded over the expert
  axis this becomes the classic all_to_all pair; the replicated-batch
  form keeps one collective.)
- gradients flow through gates and expert weights (straight-through on
  the routing choice, the standard top-1 formulation); everything is
  pure lax inside ``shard_map``, so XLA lowers dispatch to ICI
  collectives.

``moe_ffn`` is the single-device (unsharded) reference; ``make_moe``
returns the expert-parallel version over a mesh axis. Parity between
the two is pinned in ``tests/test_moe.py``.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _route(x, wg, n_experts):
    """Top-1 routing: (expert_id[B], gate[B]) with softmax gates."""
    logits = x @ wg                       # [B, E]
    probs = jax.nn.softmax(logits, axis=-1)
    eid = jnp.argmax(probs, axis=-1)
    gate = jnp.take_along_axis(probs, eid[:, None], axis=-1)[:, 0]
    return eid, gate


def _expert_ffn(x, w1, b1, w2, b2):
    return jax.nn.relu(x @ w1 + b1) @ w2 + b2


def init_moe_params(key, d_model: int, d_hidden: int, n_experts: int
                    ) -> Dict[str, jnp.ndarray]:
    k1, k2, k3 = jax.random.split(key, 3)
    s1 = 1.0 / jnp.sqrt(d_model)
    s2 = 1.0 / jnp.sqrt(d_hidden)
    return {
        "wg": jax.random.normal(k1, (d_model, n_experts)) * s1,
        "w1": jax.random.normal(k2, (n_experts, d_model, d_hidden)) * s1,
        "b1": jnp.zeros((n_experts, d_hidden)),
        "w2": jax.random.normal(k3, (n_experts, d_hidden, d_model)) * s2,
        "b2": jnp.zeros((n_experts, d_model)),
    }


def _dispatch_plan(eid, n_experts, capacity, live=None):
    """Position of each token within its expert's capacity slots, and a
    keep-mask for tokens under capacity (static shapes throughout).

    ``live`` ([B] bool/0-1, optional) marks real tokens: dead (padded)
    positions claim no capacity slot and are excluded from ``keep``, so
    a padded batch routes identically to its unpadded equivalent."""
    onehot = jax.nn.one_hot(eid, n_experts, dtype=jnp.int32)   # [B, E]
    if live is not None:
        onehot = onehot * live.astype(jnp.int32)[:, None]
    pos = jnp.cumsum(onehot, axis=0) * onehot                  # 1-based
    slot = jnp.sum(pos, axis=-1) - 1                           # [B]
    keep = (slot < capacity) & (slot >= 0)
    return slot, keep


def moe_ffn(params, x, capacity: int, live=None):
    """Single-device reference: identical math to the sharded version
    (capacity clipping included), dense per-expert batches. ``live``
    excludes masked/padded tokens from dispatch (they produce zeros)."""
    n_experts = params["wg"].shape[-1]
    eid, gate = _route(x, params["wg"], n_experts)
    slot, keep = _dispatch_plan(eid, n_experts, capacity, live)
    d = x.shape[-1]
    # scatter tokens into [E, capacity, d] buffers
    buf = jnp.zeros((n_experts, capacity, d), x.dtype)
    buf = buf.at[eid, jnp.clip(slot, 0, capacity - 1)].add(
        x * keep[:, None].astype(x.dtype))
    out_buf = jax.vmap(_expert_ffn)(buf, params["w1"], params["b1"],
                                    params["w2"], params["b2"])
    y = out_buf[eid, jnp.clip(slot, 0, capacity - 1)]
    return y * (gate * keep.astype(x.dtype))[:, None]


def make_moe(mesh: Mesh, axis: str, n_experts: int, capacity: int):
    """Expert-parallel MoE over ``axis`` (one or more experts per device;
    ``n_experts`` must be divisible by the axis size). Returns
    ``fn(params, x) -> y`` with params sharded expert-major on ``axis``
    and ``x`` fully REPLICATED (in_specs pins it): every device routes
    the whole batch and keeps only its experts' buffers. Shard the batch
    upstream over the data axis and call this per data-shard if DP is
    also in play. ``fn(params, x, live)`` takes a [B] 0-1 live mask
    (pass ones for fully-dense batches): dead/padded tokens claim no
    capacity slot, matching ``moe_ffn``'s ragged semantics exactly."""
    n_dev = mesh.shape[axis]
    if n_experts % n_dev:
        raise ValueError(f"{n_experts} experts over {n_dev} devices")
    e_local = n_experts // n_dev

    def local(params, x, live):
        # x: the full (replicated-over-axis) token batch [B, d]
        eid, gate = _route(x, params["wg"], n_experts)
        slot, keep = _dispatch_plan(eid, n_experts, capacity, live)
        d = x.shape[-1]
        # build every expert's capacity buffer locally (the batch is
        # replicated, so all copies agree); keep this device's slice —
        # the only collective is the all_gather of expert outputs below
        buf = jnp.zeros((n_experts, capacity, d), x.dtype)
        buf = buf.at[eid, jnp.clip(slot, 0, capacity - 1)].add(
            x * keep[:, None].astype(x.dtype))
        # [E, cap, d] -> [n_dev, e_local, cap, d]; device i keeps slice i
        buf = buf.reshape(n_dev, e_local, capacity, d)
        # psum-of-scatter: every device built the full buffer from ITS
        # replicated batch copy; they are identical, so just slice
        idx = lax.axis_index(axis)
        mine = lax.dynamic_index_in_dim(buf, idx, axis=0, keepdims=False)
        out_local = jax.vmap(_expert_ffn)(
            mine, params["w1"], params["b1"], params["w2"], params["b2"])
        # gather every expert's outputs back to every device
        out_all = lax.all_gather(out_local, axis)  # [n_dev, e_local, cap, d]
        out_all = out_all.reshape(n_experts, capacity, d)
        y = out_all[eid, jnp.clip(slot, 0, capacity - 1)]
        return y * (gate * keep.astype(x.dtype))[:, None]

    from paddle_tpu.parallel.mesh import shard_map_compat
    fn = shard_map_compat(
        local, mesh=mesh,
        in_specs=({"wg": P(), "w1": P(axis), "b1": P(axis),
                   "w2": P(axis), "b2": P(axis)}, P(), P()),
        out_specs=P(), check_vma=False)
    jitted = jax.jit(fn)

    def call(params, x, live=None):
        if live is None:
            live = jnp.ones((x.shape[0],), x.dtype)
        return jitted(params, x, live)

    return call


def shard_moe_params(params, mesh: Mesh, axis: str):
    """Place MoE params: router replicated, experts split over ``axis``."""
    out = {}
    for k, v in params.items():
        spec = P() if k == "wg" else P(axis)
        out[k] = jax.device_put(v, NamedSharding(mesh, spec))
    return out
