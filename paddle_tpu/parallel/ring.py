"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The 2017 reference has NO sequence parallelism (SURVEY §2 checklist — its
long-sequence story is ragged batching only), so this module is pure
capability-add, designed TPU-first: both schemes run inside ``shard_map``
over a named mesh axis holding sequence shards, and XLA lowers the
communication to ICI collectives.

- ``ring_attention``: each device keeps its Q shard and rotates the KV
  shard around the ring (``lax.ppermute``), accumulating flash-style
  online-softmax state. Compute on the current block overlaps the
  next block's transfer (XLA pipelines the ppermute). Memory per device:
  O(T/P); total traffic: each KV shard crosses each ICI hop once per
  step — the classic Ring Attention schedule.
- ``ulysses_attention``: ``lax.all_to_all`` re-shards [seq → heads], so
  each device holds N/P full-length heads, runs ordinary (flash)
  attention locally, then all-to-alls back. Cheaper for moderate T with
  enough heads; requires num_heads % P == 0.

Both are differentiable (pure lax ops + the blockwise kernel from
ops/attention.py) and mask/causal-aware with *global* positions, so the
sharded result equals single-device attention bit-for-bit up to fp
reassociation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.ops.attention import blockwise_attention, flash_attention

_NEG = -1e9


def _local_attn_stats(q, k, v, kv_mask, causal, scale, q_off, k_off):
    """One Q-shard vs one KV-block attention with un-normalized
    accumulator: returns (acc, m, l) for online-softmax merging.
    q [B,N,Tq,D], k/v [B,N,Tk,D], kv_mask [B,Tk] or None; q_off/k_off are
    the global positions of element 0 (for causal masking)."""
    s = jnp.einsum("bnqd,bnkd->bnqk", q, k) * scale
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :] > 0, s, _NEG)
    if causal:
        qi = q_off + jnp.arange(q.shape[2])[:, None]
        kj = k_off + jnp.arange(k.shape[2])[None, :]
        s = jnp.where(kj <= qi, s, _NEG)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bnqk,bnkd->bnqd", p, v)
    return acc, m, l


def _merge_stats(acc1, m1, l1, acc2, m2, l2):
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    return (acc1 * a1[..., None] + acc2 * a2[..., None],
            m, l1 * a1 + l2 * a2)


def ring_attention(q, k, v, axis_name, kv_mask=None, causal=False,
                   scale=None):
    """Ring attention over the mesh axis ``axis_name``. Must be called
    inside ``shard_map``; q/k/v are the per-device sequence shards
    [B, N, T/P, D], kv_mask the matching [B, T/P] shard."""
    P = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    Tl = q.shape[2]
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    q_off = idx * Tl

    B, N, _, D = q.shape
    acc = jnp.zeros((B, N, Tl, D), jnp.float32)
    m = jnp.full((B, N, Tl), _NEG, jnp.float32)
    l = jnp.zeros((B, N, Tl), jnp.float32)
    if kv_mask is None:
        kv_mask = jnp.ones((B, Tl), q.dtype)

    perm = [(i, (i + 1) % P) for i in range(P)]

    def body(s, carry):
        acc, m, l, k_cur, v_cur, mask_cur = carry
        # KV currently resident here originated at device (idx - s) mod P
        k_off = ((idx - s) % P) * Tl
        a2, m2, l2 = _local_attn_stats(q, k_cur, v_cur, mask_cur, causal,
                                       scale, q_off, k_off)
        acc, m, l = _merge_stats(acc, m, l, a2, m2, l2)
        if s < P - 1:  # last step's rotation would be dead ICI traffic
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
            mask_cur = lax.ppermute(mask_cur, axis_name, perm)
        return acc, m, l, k_cur, v_cur, mask_cur

    # static unroll over ring steps: P is small and static, and unrolling
    # lets XLA overlap each step's ppermute with the previous compute
    carry = (acc, m, l, k, v, kv_mask)
    for s in range(P):
        carry = body(s, carry)
    acc, m, l = carry[:3]
    return (acc / l[..., None]).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name, kv_mask=None, causal=False,
                      scale=None):
    """Ulysses sequence parallelism over ``axis_name`` (inside shard_map):
    all-to-all [B, N, T/P, D] → [B, N/P, T, D], local flash attention,
    all-to-all back. num_heads must divide by the axis size."""
    P = lax.psum(1, axis_name)
    N = q.shape[1]
    assert N % P == 0, f"heads {N} not divisible by seq-parallel degree {P}"
    # concat_dim_to_split... all_to_all(split heads, concat sequence)
    def fwd(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def bwd(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = fwd(q), fwd(k), fwd(v)
    full_mask = (lax.all_gather(kv_mask, axis_name, axis=1, tiled=True)
                 if kv_mask is not None else None)
    out = flash_attention(qh, kh, vh, full_mask, causal=causal, scale=scale)
    return bwd(out)


def make_ring_attention(mesh, axis_name, kind="ring", causal=False):
    """Build a jittable full-tensor attention fn sharded over ``mesh``'s
    ``axis_name`` (sequence dim). Inputs/outputs are global [B, N, T, D]
    (+ optional kv_mask [B, T]); sharding + collectives happen inside."""
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.parallel.mesh import shard_map_compat

    inner = ring_attention if kind == "ring" else ulysses_attention
    spec = P(None, None, axis_name, None)
    mask_spec = P(None, axis_name)

    @functools.partial(
        shard_map_compat, mesh=mesh,
        in_specs=(spec, spec, spec, mask_spec),
        out_specs=spec, check_vma=False)
    def sharded(q, k, v, kv_mask):
        return inner(q, k, v, axis_name, kv_mask=kv_mask, causal=causal)

    def fn(q, k, v, kv_mask=None):
        if kv_mask is None:
            kv_mask = jnp.ones((q.shape[0], q.shape[2]), q.dtype)
        return sharded(q, k, v, kv_mask)

    return fn
