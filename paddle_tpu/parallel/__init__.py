from paddle_tpu.parallel.mesh import (  # noqa: F401
    create_mesh, replicate, shard_batch, shard_params)
