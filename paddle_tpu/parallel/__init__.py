from paddle_tpu.parallel.layout import SpecLayout  # noqa: F401
from paddle_tpu.parallel.mesh import (  # noqa: F401
    create_mesh, create_multislice_mesh, param_shardings, replicate,
    shard_batch, shard_opt_state, shard_params)
