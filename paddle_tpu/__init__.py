"""paddle_tpu — a TPU-native deep learning framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of PaddlePaddle
(mid-2017, the "v2 API + v1 trainer" generation): the same layer vocabulary,
config DSL, trainer semantics, and distributed-training behaviors, built
TPU-first:

- compute is jax.numpy / lax / Pallas, compiled by XLA onto the MXU;
- the per-batch train step is one jitted pure function
  ``(params, opt_state, batch) -> (params, opt_state, metrics)``;
- parallelism is expressed as shardings over a ``jax.sharding.Mesh``
  (data/model axes) with XLA collectives over ICI, replacing the reference's
  thread-ring (``paddle/gserver/gradientmachines/MultiGradientMachine.h``)
  and parameter-server (``paddle/pserver``) paths;
- ragged sequences become padded+masked batches with ``lax.scan`` recurrence,
  replacing offset-based ragged batching (``paddle/parameter/Argument.h:84``).

Top-level namespaces mirror the reference's Python v2 API
(``/root/reference/python/paddle/v2/__init__.py``).
"""

from paddle_tpu import config  # noqa: F401
from paddle_tpu import core  # noqa: F401
from paddle_tpu import data  # noqa: F401
from paddle_tpu import layers  # noqa: F401
from paddle_tpu import optim  # noqa: F401
from paddle_tpu import parallel  # noqa: F401
from paddle_tpu import trainer  # noqa: F401
from paddle_tpu import models  # noqa: F401
from paddle_tpu import serving  # noqa: F401

__version__ = "0.1.0"

_GLOBAL_SETTINGS = {
    "use_tpu": True,
    "trainer_count": 1,
    "seed": 0,
    "compute_dtype": "float32",
    "log_period": 100,
}


def init(**kwargs):
    """Process-level initialization, mirroring ``paddle.init(**kwargs)``.

    The reference turns kwargs into gflags consumed by the C++ trainer
    (``python/paddle/v2/__init__.py`` -> ``utils/Flags.cpp:18-80``). Here the
    engine is JAX, so flags become a settings dict read by the trainer and
    parallel layers. Unknown kwargs are accepted and stored (the reference
    accepts any registered gflag).
    """
    _GLOBAL_SETTINGS.update(kwargs)
    return _GLOBAL_SETTINGS


def settings():
    return _GLOBAL_SETTINGS
