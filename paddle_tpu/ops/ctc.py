"""CTC alpha-beta recursion as a Pallas TPU kernel.

The reference computes CTC forward-backward per sequence on the host
(`paddle/gserver/layers/LinearChainCTC.cpp:55-150`). Here the whole batch
runs on device over the padded extended label sequence (S = 2L+1,
blank-interleaved, `chain.py` builds it): the kernel fuses the three-way
shifted logsumexp + emission add per time step, carrying alpha [B, S] in
VMEM across the sequentially-executed grid; the S axis pads to the
128-lane width.

The op consumes *pre-gathered* emissions ``emit[b, t, s] =
log_probs[b, t, ext[b, s]]`` — the gather (and its scatter-add transpose
back into the [B, T, C] log-prob tensor) stays outside in XLA autodiff
land, so the hand-written VJP only handles the DP itself: the beta
recursion over the alphas saved by the forward kernel, with
d ll / d emit_t[s] = exp(alpha_t[s] + beta_t[s] - ll)
(the state posterior; beta excludes its own step's emission, so emit_t is
counted exactly once, inside alpha).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.ops import common

NEG = common.NEG
LANE = common.LANE


def _lse3(a, b, c):
    m = jnp.maximum(jnp.maximum(a, b), c)
    m_safe = jnp.maximum(m, NEG)  # all-NEG columns stay NEG, no nan
    return m_safe + jnp.log(jnp.exp(a - m_safe) + jnp.exp(b - m_safe)
                            + jnp.exp(c - m_safe))


def _shift1(x):
    return jnp.concatenate([jnp.full_like(x[:, :1], NEG), x[:, :-1]], axis=1)


def _shift2(x):
    return jnp.concatenate([jnp.full_like(x[:, :2], NEG), x[:, :-2]], axis=1)


def _step(alpha, emit_t, can_skip, valid_s):
    a1 = _shift1(alpha)
    a2 = jnp.where(can_skip > 0, _shift2(alpha), NEG)
    nxt = _lse3(alpha, a1, a2) + emit_t
    return jnp.where(valid_s > 0, nxt, NEG)


def ctc_ll_ref(emit, in_mask, valid_s, can_skip, ext_lens):
    """lax.scan reference. emit [B,T,S] gathered log-probs; in_mask [B,T];
    valid_s/can_skip [B,S] floats; ext_lens [B] ints. Returns ll [B]."""
    B, T, S = emit.shape
    s_idx = jnp.arange(S)[None, :]
    alpha = jnp.where((s_idx <= 1) & (valid_s > 0), emit[:, 0], NEG)

    def body(alpha, inp):
        e_t, m_t = inp
        nxt = _step(alpha, e_t, can_skip, valid_s)
        return jnp.where(m_t[:, None] > 0, nxt, alpha), None

    es = jnp.swapaxes(emit, 0, 1)[1:]
    ms = jnp.swapaxes(in_mask, 0, 1)[1:]
    alpha, _ = lax.scan(body, alpha, (es, ms))
    return _final_ll(alpha, ext_lens)


def _final_ll(alpha, ext_lens):
    last = jnp.take_along_axis(
        alpha, jnp.maximum(ext_lens - 1, 0)[:, None], axis=1)[:, 0]
    last2 = jnp.take_along_axis(
        alpha, jnp.maximum(ext_lens - 2, 0)[:, None], axis=1)[:, 0]
    last2 = jnp.where(ext_lens >= 2, last2, NEG)
    m = jnp.maximum(last, last2)
    return m + jnp.log(jnp.exp(last - m) + jnp.exp(last2 - m))


# ---------------------------------------------------------------- pallas

def _ctc_kernel(emit_ref, mask_ref, skip_ref, valid_ref, a0_ref,
                alphas_ref, alpha_s):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        alpha_s[:] = a0_ref[:]

    alpha = alpha_s[:]
    nxt = _step(alpha, emit_ref[0], skip_ref[:], valid_ref[:])
    alpha = jnp.where(mask_ref[0] > 0, nxt, alpha)
    alpha_s[:] = alpha
    alphas_ref[0] = alpha


def _ctc_alphas_pallas(emit, in_mask, valid_s, can_skip):
    B, T, S = emit.shape
    dt = emit.dtype
    s_idx = jnp.arange(S)[None, :]
    alpha0 = jnp.where((s_idx <= 1) & (valid_s > 0), emit[:, 0], NEG)
    t_block, full = common.time_block, common.resident_block
    es = jnp.swapaxes(emit, 0, 1)
    ms = jnp.swapaxes(in_mask, 0, 1)[:, :, None]
    ms = ms.at[0].set(0.0)  # step 0 only records alpha_0
    alphas = pl.pallas_call(
        _ctc_kernel,
        grid=(T,),
        in_specs=[t_block(B, S), t_block(B, 1), full(B, S), full(B, S),
                  full(B, S)],
        out_specs=t_block(B, S),
        out_shape=jax.ShapeDtypeStruct((T, B, S), dt),
        scratch_shapes=[pltpu.VMEM((B, S), dt)],
        interpret=common.interpret(),
    )(es, ms, can_skip, valid_s, alpha0)
    return jnp.swapaxes(alphas, 0, 1)  # [B,T,S]


@jax.custom_vjp
def _ctc_core(emit, in_mask, valid_s, can_skip, ext_lens):
    alphas = _ctc_alphas_pallas(emit, in_mask, valid_s, can_skip)
    return _final_ll(alphas[:, -1], ext_lens)


def _ctc_fwd(emit, in_mask, valid_s, can_skip, ext_lens):
    alphas = _ctc_alphas_pallas(emit, in_mask, valid_s, can_skip)
    ll = _final_ll(alphas[:, -1], ext_lens)
    return ll, (emit, in_mask, valid_s, can_skip, ext_lens, alphas, ll)


def _ctc_bwd(res, g):
    """Beta recursion (suffix scores EXCLUDING the step-t emission):
    beta_{T-1}[s] = 0 at s in {len-1, len-2}, else -inf; going backwards
    beta_t[s] = lse3(beta_{t+1}[s], beta_{t+1}[s+1],
                     beta_{t+1}[s+2] if skippable) + emit_{t+1}[.] folded
    as forward-shifted terms. Frozen where step t+1 is padding."""
    emit, in_mask, valid_s, can_skip, ext_lens, alphas, ll = res
    B, T, S = emit.shape
    s_idx = jnp.arange(S)[None, :]
    beta_last = jnp.where(
        (s_idx == jnp.maximum(ext_lens - 1, 0)[:, None])
        | ((s_idx == jnp.maximum(ext_lens - 2, 0)[:, None])
           & (ext_lens[:, None] >= 2)),
        0.0, NEG)

    def shift_m1(x):  # x[s+1]
        return jnp.concatenate(
            [x[:, 1:], jnp.full_like(x[:, :1], NEG)], axis=1)

    def shift_m2(x):  # x[s+2]
        return jnp.concatenate(
            [x[:, 2:], jnp.full_like(x[:, :2], NEG)], axis=1)

    # can_skip[s] gates the s-2 -> s jump; from state s the jump to s+2 is
    # allowed iff can_skip[s+2]
    skip_fwd = shift_m2(jnp.where(can_skip > 0, 0.0, NEG))

    def body(beta, inp):
        e_next, m_next = inp  # emission + mask of step t+1
        y = beta + e_next  # beta'_{t+1}[s] including its own emission
        stay = y
        up1 = shift_m1(y)
        up2 = shift_m2(y) + skip_fwd
        prev = _lse3(stay, up1, up2)
        prev = jnp.where(valid_s > 0, prev, NEG)
        return jnp.where(m_next[:, None] > 0, prev, beta), beta

    es = jnp.swapaxes(emit, 0, 1)[1:]
    ms = jnp.swapaxes(in_mask, 0, 1)[1:]
    beta0, betas_rest = lax.scan(body, beta_last, (es, ms), reverse=True)
    betas = jnp.concatenate([beta0[None], betas_rest], axis=0)  # [T,B,S]
    betas = jnp.swapaxes(betas, 0, 1)

    # d ll / d emit_t[s] = P(state s at step t) = exp(alpha_t + beta_t - ll)
    # (alpha covers emissions <= t, beta covers > t, so emit_t is counted
    # exactly once, inside alpha)
    post = jnp.exp(jnp.minimum(alphas + betas - ll[:, None, None], 30.0))
    demit = g[:, None, None] * post * in_mask[:, :, None]
    return demit, None, None, None, None


_ctc_core.defvjp(_ctc_fwd, _ctc_bwd)


# ---------------------------------------------------------------- public

def ctc_ll(emit, in_mask, valid_s, can_skip, ext_lens):
    """Log-likelihood [B] of the CTC paths. Pallas on TPU (S padded to the
    128-lane width by the caller or here), lax.scan elsewhere."""
    B, T, S = emit.shape
    Sp = ((S + LANE - 1) // LANE) * LANE
    itemsize = jnp.dtype(emit.dtype).itemsize
    resident = itemsize * 6 * B * Sp
    if not common.use_pallas(resident):
        return ctc_ll_ref(emit, in_mask, valid_s, can_skip, ext_lens)
    if Sp != S:
        pc = Sp - S
        emit = jnp.pad(emit, ((0, 0), (0, 0), (0, pc)), constant_values=NEG)
        valid_s = jnp.pad(valid_s, ((0, 0), (0, pc)))
        can_skip = jnp.pad(can_skip, ((0, 0), (0, pc)))
    return _ctc_core(emit, in_mask, valid_s, can_skip, ext_lens)
