"""Attention kernels: Pallas flash attention + blockwise-scan reference.

The 2017 reference has no fused attention (its only attention is the
composite `simple_attention` in `trainer_config_helpers/networks.py`);
this module is where the TPU build exceeds it, and it is the per-device
compute block of ring attention (parallel/ring.py): sequence parallelism
needs an attention that consumes KV in blocks with online-softmax running
state, which is exactly the flash decomposition.

Three tiers:
- ``mha_reference`` — plain softmax attention, ground truth for tests.
- ``blockwise_attention`` — pure-JAX ``lax.scan`` over KV blocks with
  online softmax (max/sum running stats). Memory O(T_q·block) instead of
  O(T_q·T_k); differentiable by autodiff; runs anywhere.
- ``flash_attention`` — Pallas kernel: grid (batch·heads, q-blocks,
  kv-blocks), kv innermost so the accumulator lives in VMEM scratch across
  the kv sweep. Backward = recompute via ``jax.vjp`` of
  ``blockwise_attention`` (flash-bwd recompute strategy).

All take [B, N, T, D] and an optional kv validity mask [B, T_k] plus a
``causal`` flag.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.ops import common

_NEG = -1e9


def mha_reference(q, k, v, kv_mask=None, causal=False, scale=None):
    """Plain attention. q [B,N,Tq,D], k/v [B,N,Tk,D], kv_mask [B,Tk]."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("bnqd,bnkd->bnqk", q, k) * scale
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :] > 0, s, _NEG)
    if causal:
        Tq, Tk = s.shape[-2], s.shape[-1]
        qi = jnp.arange(Tq)[:, None] + (Tk - Tq)
        kj = jnp.arange(Tk)[None, :]
        s = jnp.where(kj <= qi, s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bnqk,bnkd->bnqd", p, v)


def blockwise_attention(q, k, v, kv_mask=None, causal=False, scale=None,
                        block_k=512):
    """Memory-efficient attention: lax.scan over KV blocks with online
    softmax. Differentiable; the ground-truth backward for flash."""
    B, N, Tq, D = q.shape
    Tk = k.shape[2]
    scale = scale if scale is not None else D ** -0.5
    block_k = min(block_k, Tk)
    pad = (-Tk) % block_k
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
        base = (kv_mask if kv_mask is not None
                else jnp.ones((B, Tk), q.dtype))
        kv_mask = jnp.pad(base, ((0, 0), (0, pad)))
    nk = k.shape[2] // block_k
    kb = k.reshape(B, N, nk, block_k, D).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(B, N, nk, block_k, D).transpose(2, 0, 1, 3, 4)
    mb = (kv_mask.reshape(B, nk, block_k).transpose(1, 0, 2)
          if kv_mask is not None else None)
    qi = jnp.arange(Tq)[:, None] + (Tk - Tq)

    def body(carry, inp):
        acc, m_run, l_run = carry
        idx, k_t, v_t, msk = inp
        s = jnp.einsum("bnqd,bnkd->bnqk", q, k_t) * scale
        if msk is not None:
            s = jnp.where(msk[:, None, None, :] > 0, s, _NEG)
        if causal:
            kj = idx * block_k + jnp.arange(block_k)[None, :]
            s = jnp.where(kj <= qi, s, _NEG)
        m_new = jnp.maximum(m_run, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m_run - m_new)
        l_new = l_run * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum("bnqk,bnkd->bnqd", p, v_t)
        return (acc, m_new, l_new), None

    acc0 = jnp.zeros((B, N, Tq, D), jnp.float32)
    m0 = jnp.full((B, N, Tq), _NEG, jnp.float32)
    l0 = jnp.zeros((B, N, Tq), jnp.float32)
    if mb is None:
        (acc, m_run, l_run), _ = lax.scan(
            lambda c, i: body(c, (i[0], i[1], i[2], None)), (acc0, m0, l0),
            (jnp.arange(nk), kb, vb))
    else:
        (acc, m_run, l_run), _ = lax.scan(body, (acc0, m0, l0),
                                          (jnp.arange(nk), kb, vb, mb))
    return (acc / l_run[..., None]).astype(q.dtype)


# ---------------------------------------------------------------- pallas

def _flash_kernel(tq_orig, tk_orig, scale, causal,
                  q_ref, k_ref, v_ref, mask_ref,
                  o_ref, acc_s, m_s, l_s):
    kb = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kb == 0)
    def _():
        acc_s[:] = jnp.zeros_like(acc_s)
        m_s[:] = jnp.full_like(m_s, _NEG)
        l_s[:] = jnp.zeros_like(l_s)

    q = q_ref[0]          # [Bq, D]
    k = k_ref[0]          # [Bk, D]
    v = v_ref[0]
    Bq, Bk = q.shape[0], k.shape[0]
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
    msk = mask_ref[0]     # [1, Bk] validity of this kv block
    s = jnp.where(msk > 0, s, _NEG)
    if causal:
        qb = pl.program_id(1)
        qi = (qb * Bq + lax.broadcasted_iota(jnp.int32, (Bq, Bk), 0)
              + (tk_orig - tq_orig))
        kj = kb * Bk + lax.broadcasted_iota(jnp.int32, (Bq, Bk), 1)
        s = jnp.where(kj <= qi, s, _NEG)
    m_prev = m_s[:, 0:1]                                     # [Bq, 1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m_prev - m_new)                          # [Bq, 1]
    l_s[:, 0:1] = l_s[:, 0:1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_s[:] = (acc_s[:] * alpha
                + jnp.dot(p.astype(v.dtype), v,
                          preferred_element_type=jnp.float32))
    m_s[:, 0:1] = m_new

    @pl.when(kb == nk - 1)
    def _():
        o_ref[0] = (acc_s[:] / l_s[:, 0:1]).astype(o_ref.dtype)


def _flash_forward(q, k, v, kv_mask, causal, scale, block_q, block_k):
    B, N, Tq, D = q.shape
    Tk = k.shape[2]
    tk_orig = Tk
    block_q = min(block_q, Tq)
    block_k = min(block_k, Tk)
    pad_q = (-Tq) % block_q
    pad_k = (-Tk) % block_k
    if kv_mask is None:
        kv_mask = jnp.ones((B, Tk), jnp.float32)
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        kv_mask = jnp.pad(kv_mask, ((0, 0), (0, pad_k)))
    Tqp, Tkp = q.shape[2], k.shape[2]
    qf = q.reshape(B * N, Tqp, D)
    kf = k.reshape(B * N, Tkp, D)
    vf = v.reshape(B * N, Tkp, D)
    nq, nk = Tqp // block_q, Tkp // block_k
    kernel = functools.partial(_flash_kernel, Tq, tk_orig, scale, causal)
    out = pl.pallas_call(
        kernel,
        grid=(B * N, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bn, qb, kb: (bn, qb, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D), lambda bn, qb, kb: (bn, kb, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D), lambda bn, qb, kb: (bn, kb, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k), lambda bn, qb, kb: (bn // N, 0, kb),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, D),
                               lambda bn, qb, kb: (bn, qb, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B * N, Tqp, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=common.interpret(),
    )(qf, kf, vf, kv_mask[:, None, :])
    return out.reshape(B, N, Tqp, D)[:, :, :Tq]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_core(q, k, v, kv_mask, causal, scale, block_q, block_k):
    return _flash_forward(q, k, v, kv_mask, causal, scale, block_q, block_k)


def _flash_fwd(q, k, v, kv_mask, causal, scale, block_q, block_k):
    out = _flash_forward(q, k, v, kv_mask, causal, scale, block_q, block_k)
    return out, (q, k, v, kv_mask)


def _flash_bwd(causal, scale, block_q, block_k, res, g):
    q, k, v, kv_mask = res
    # Flash-style recompute backward: autodiff the blockwise formulation.
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_attention(
            q_, k_, v_, kv_mask, causal=causal, scale=scale,
            block_k=block_k), q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None


_flash_core.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, kv_mask=None, causal=False, scale=None,
                    block_q=256, block_k=256):
    """Flash attention. Pallas on TPU, blockwise-scan elsewhere."""
    D = q.shape[-1]
    scale = scale if scale is not None else D ** -0.5
    resident = jnp.dtype(q.dtype).itemsize * (
        3 * min(block_k, k.shape[2]) * D + 2 * min(block_q, q.shape[2]) * D)
    if not common.use_pallas(resident):
        return blockwise_attention(q, k, v, kv_mask, causal=causal,
                                   scale=scale, block_k=block_k)
    return _flash_core(q, k, v, kv_mask, causal, scale, block_q, block_k)
