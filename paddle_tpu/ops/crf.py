"""Linear-chain CRF partition function as a Pallas TPU kernel.

The reference computes the CRF forward-backward on the host, one sequence
at a time (`paddle/gserver/layers/LinearChainCRF.cpp:28-102`). The TPU
design keeps the whole batch on device and makes the time recursion MXU
work: in log space the alpha update is

    alpha_{t}[b, j] = logsumexp_i(alpha_{t-1}[b, i] + trans[i, j]) + x_t[b, j]

which, max-shifted, is an exp-space matrix product

    m[b]   = max_i alpha_{t-1}[b, i]
    S      = exp(alpha_{t-1} - m) @ exp(trans - tm)        # [B,C] x [C,C]
    alpha_t = log(S) + m + tm + x_t

so each step is one [B,C]x[C,C] matmul on the systolic array plus VPU
elementwise work — the same "keep the weight resident, fuse the step" shape
as the fused LSTM kernel (`ops/lstm.py`). The class axis is padded to the
128-lane width with -inf emissions/transitions, which round-trip through
the exp-space matmul as exact zeros.

Backward is the analytic beta recursion (marginals = d log Z), run as a
`lax.scan` over the alphas the forward kernel saved — no autodiff through
the time loop, mirroring the cuDNN-style "save activations" strategy used
by the other fused kernels.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.ops import common

NEG = common.NEG
LANE = common.LANE


def _pad_classes(x, trans, a, b):
    """Pad the class axis to a LANE multiple with -inf scores."""
    C = x.shape[-1]
    Cp = ((C + LANE - 1) // LANE) * LANE
    if Cp == C:
        return x, trans, a, b, C
    pc = Cp - C
    x = jnp.pad(x, ((0, 0), (0, 0), (0, pc)), constant_values=NEG)
    trans = jnp.pad(trans, ((0, pc), (0, pc)), constant_values=NEG)
    a = jnp.pad(a, (0, pc), constant_values=NEG)
    b = jnp.pad(b, (0, pc), constant_values=NEG)
    return x, trans, a, b, C


def _step(alpha, trans_shift, tm, x_t):
    """One max-shifted exp-space alpha update (shared by ref and bwd)."""
    m = jnp.max(alpha, axis=-1, keepdims=True)
    s = jnp.exp(alpha - m) @ trans_shift
    return jnp.log(jnp.maximum(s, 1e-37)) + m + tm + x_t


def crf_log_z_ref(x, mask, trans, a, b):
    """lax.scan reference. x [B,T,C], mask [B,T], trans [C,C], a/b [C].
    Returns log Z [B] (alpha frozen on padded steps)."""
    tm = jnp.max(trans)
    trans_shift = jnp.exp(trans - tm)
    alpha0 = a[None, :] + x[:, 0]

    def body(alpha, inp):
        x_t, m_t = inp
        nxt = _step(alpha, trans_shift, tm, x_t)
        return jnp.where(m_t[:, None] > 0, nxt, alpha), None

    xs = jnp.swapaxes(x, 0, 1)[1:]
    ms = jnp.swapaxes(mask, 0, 1)[1:]
    alpha, _ = lax.scan(body, alpha0, (xs, ms))
    m = jnp.max(alpha + b[None, :], axis=-1, keepdims=True)
    return jnp.squeeze(m, -1) + jnp.log(
        jnp.sum(jnp.exp(alpha + b[None, :] - m), axis=-1))


# ---------------------------------------------------------------- pallas fwd

def _crf_kernel(xs_ref, mask_ref, trans_ref, tm_ref, a_ref, x0_ref,
                alphas_ref, alpha_s):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        alpha_s[:] = a_ref[:] + x0_ref[:]

    alpha = alpha_s[:]
    tm = tm_ref[0, 0]
    m = jnp.max(alpha, axis=-1, keepdims=True)
    s = jnp.dot(jnp.exp(alpha - m), trans_ref[:],
                preferred_element_type=jnp.float32).astype(alpha.dtype)
    nxt = jnp.log(jnp.maximum(s, 1e-37)) + m + tm + xs_ref[0]
    alpha = jnp.where(mask_ref[0] > 0, nxt, alpha)
    alpha_s[:] = alpha
    alphas_ref[0] = alpha


def _crf_alphas_pallas(x, mask, trans, a):
    """All alphas [T,B,C] with the recursion fused in one kernel; the
    returned array includes alpha_0 at index 0 (computed in-kernel)."""
    B, T, C = x.shape
    dt = x.dtype
    tm = jnp.max(trans)
    trans_shift = jnp.exp(trans - tm)
    t_block, full = common.time_block, common.resident_block
    xs = jnp.swapaxes(x, 0, 1)  # [T,B,C]; step t consumes xs[t] (t>=1)
    ms = jnp.swapaxes(mask, 0, 1)[:, :, None]
    # grid step 0 writes alpha_0 (mask forced 0 so the update freezes),
    # steps 1..T-1 run the recursion
    ms = ms.at[0].set(0.0)
    alphas = pl.pallas_call(
        _crf_kernel,
        grid=(T,),
        in_specs=[
            t_block(B, C),                 # xs (consumed at step t)
            t_block(B, 1),                 # mask
            full(C, C),                    # exp(trans - tm), resident
            full(1, 1),                    # tm
            full(B, C),                    # a + broadcast (as [B,C])
            full(B, C),                    # x[:, 0]
        ],
        out_specs=t_block(B, C),
        out_shape=jax.ShapeDtypeStruct((T, B, C), dt),
        scratch_shapes=[pltpu.VMEM((B, C), dt)],
        interpret=common.interpret(),
    )(xs, ms, trans_shift, tm.reshape(1, 1),
      jnp.broadcast_to(a[None, :], (B, C)), x[:, 0])
    return jnp.swapaxes(alphas, 0, 1)  # [B,T,C]


# ------------------------------------------------------------- custom vjp

@functools.partial(jax.custom_vjp, nondiff_argnums=())
def _crf_core(x, mask, trans, a, b):
    alphas = _crf_alphas_pallas(x, mask, trans, a)
    last = alphas[:, -1] + b[None, :]
    m = jnp.max(last, axis=-1, keepdims=True)
    return jnp.squeeze(m, -1) + jnp.log(jnp.sum(jnp.exp(last - m), axis=-1))


def _crf_fwd(x, mask, trans, a, b):
    alphas = _crf_alphas_pallas(x, mask, trans, a)
    last = alphas[:, -1] + b[None, :]
    m = jnp.max(last, axis=-1, keepdims=True)
    log_z = jnp.squeeze(m, -1) + jnp.log(
        jnp.sum(jnp.exp(last - m), axis=-1))
    return log_z, (x, mask, trans, a, b, alphas, log_z)


def _crf_bwd(res, g):
    """Marginals via the beta recursion over saved alphas.

    d log Z / d x_t[j]      = q_t[j]            (unary marginal, masked)
    d log Z / d trans[i,j]  = sum_t p_t[i,j]    (pairwise marginal)
    d log Z / d a[i]        = q_0[i];  d/d b[j] = q_T[j]
    """
    x, mask, trans, a, b, alphas, log_z = res
    B, T, C = x.shape
    tm = jnp.max(trans)
    trans_shift = jnp.exp(trans - tm)  # [prev, next]

    # beta_T = b; beta_{t-1}[i] = logsumexp_j(trans[i,j] + x_t[j] + beta_t[j])
    # (frozen where step t is padding). Scan produces betas for t=T-1..0.
    def body(beta, inp):
        x_t, m_t = inp  # step-t emission + mask, t in [1, T-1]
        y = x_t + beta  # [B, C]
        m = jnp.max(y, axis=-1, keepdims=True)
        prev = jnp.log(jnp.maximum(
            jnp.exp(y - m) @ trans_shift.T, 1e-37)) + m + tm
        prev = jnp.where(m_t[:, None] > 0, prev, beta)
        return prev, beta

    xs = jnp.swapaxes(x, 0, 1)[1:]      # [T-1,B,C]
    ms = jnp.swapaxes(mask, 0, 1)[1:]
    beta0, betas_rest = lax.scan(
        body, jnp.broadcast_to(b[None, :], (B, C)), (xs, ms), reverse=True)
    betas = jnp.concatenate(
        [beta0[None], betas_rest], axis=0)  # [T,B,C], betas[t] for step t
    betas = jnp.swapaxes(betas, 0, 1)       # [B,T,C]

    # unary marginals (alpha_t already includes x_t; q_0 IS the start
    # marginal since alpha_0 includes a)
    q = jnp.exp(alphas + betas - log_z[:, None, None])
    q = q * mask[:, :, None]
    dx = g[:, None, None] * q

    # pairwise marginals, accumulated exactly in probability space:
    # p_t[i,j] = exp(alpha_{t-1}[i] + trans[i,j] + x_t[j] + beta_t[j] - logZ)
    # The log-score is <= a small slack above 0 (it is a path posterior),
    # so exponentiating the SUMMED score never overflows — unlike any
    # outer-product factorization, whose per-factor scale blows up for
    # strongly forbidden transitions (trans[i,j] ~ -1e4). One [B,C,C]
    # block per step, scanned over time.
    a_prev = jnp.swapaxes(alphas[:, :-1], 0, 1)       # [T-1,B,C] (i axis)
    r_next = jnp.swapaxes(x[:, 1:] + betas[:, 1:], 0, 1)  # [T-1,B,C] (j)
    pair_m = jnp.swapaxes(mask[:, 1:] * mask[:, :-1], 0, 1)  # [T-1,B]

    def pair_body(acc, inp):
        a_t, r_t, m_t = inp
        s = (a_t[:, :, None] + trans[None] + r_t[:, None, :]
             - log_z[:, None, None])
        p = jnp.exp(jnp.minimum(s, 30.0)) * (m_t * g)[:, None, None]
        return acc + jnp.sum(p, axis=0), None

    dtrans, _ = lax.scan(pair_body, jnp.zeros_like(trans),
                         (a_prev, r_next, pair_m))

    da = jnp.sum(g[:, None] * q[:, 0], axis=0)
    # end marginal: probability mass of the state at the last real step.
    # With frozen alphas, alpha_{T-1} holds the final state, so
    # q_end = exp(alpha_last + b - logZ)
    last = alphas[:, -1] + b[None, :]
    q_end = jnp.exp(last - log_z[:, None])
    db = jnp.sum(g[:, None] * q_end, axis=0)
    return dx, None, dtrans, da, db


_crf_core.defvjp(_crf_fwd, _crf_bwd)


# ---------------------------------------------------------------- public

def crf_log_z(x, mask, trans, a, b):
    """log Z [B] for a batch of linear-chain CRFs. Pallas on TPU (class
    axis padded to the 128-lane width), lax.scan elsewhere."""
    B, T, C = x.shape
    itemsize = jnp.dtype(x.dtype).itemsize
    Cp = ((C + LANE - 1) // LANE) * LANE
    resident = itemsize * (Cp * Cp + 4 * B * Cp)
    if not common.use_pallas(resident):
        return crf_log_z_ref(x, mask, trans, a, b)
    xp, transp, ap, bp, _ = _pad_classes(x, trans, a, b)
    return _crf_core(xp, mask, transp, ap, bp)
