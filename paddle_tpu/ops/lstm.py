"""Fused LSTM sequence kernel (Pallas) with analytic backward.

TPU-native equivalent of the reference's fused LSTM cell kernels
(`paddle/cuda/include/hl_gpu_lstm.cuh:46-67`, driven per-timestep by
`LstmLayer.cpp`): the whole recurrence runs as ONE Pallas kernel — the grid
iterates time (TPU grids execute sequentially), the recurrent weight stays
resident in VMEM across all T steps, and each step fuses the [B,H]x[H,4H]
recurrent matmul (MXU) with the gate nonlinearities (VPU). The input
projection x·W_in (the big MXU matmul) happens outside, batched over all
timesteps, exactly as the reference splits `Layer::forward` projection from
the fused cell.

Cell math (reference gate order [input, input_gate, forget_gate,
output_gate], peephole diagonals checkI/F/O):

    i  = tanh(a_i)
    ig = sigmoid(a_ig + c_prev * pI)
    fg = sigmoid(a_fg + c_prev * pF)
    c  = i*ig + c_prev*fg
    og = sigmoid(a_og + c * pO)
    h  = og * tanh(c)

Padded timesteps (mask==0) hold the carried state; outputs are zeroed —
this preserves the reference's ragged-sequence semantics
(`Argument.sequenceStartPositions`) in a static-shape layout.

Backward is an analytic reverse-time `lax.scan` over residuals saved by the
forward kernel (activated gates + state chains), mirroring the cuDNN-style
"save gates, no recompute" strategy.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.ops import common


def lstm_sequence_ref(xs, mask, w, gate_bias, check_i, check_f, check_o,
                      h0, c0):
    """Pure lax.scan reference. xs [T,B,4H] (pre-projected inputs), mask
    [T,B], w [H,4H]. Returns (ys [T,B,H], hT, cT)."""
    H = h0.shape[-1]

    def step(carry, inp):
        h, c = carry
        x_t, m_t = inp
        gates = x_t + h @ w + gate_bias
        a_i, a_ig, a_fg, a_og = jnp.split(gates, 4, axis=-1)
        i = jnp.tanh(a_i)
        ig = jax.nn.sigmoid(a_ig + c * check_i)
        fg = jax.nn.sigmoid(a_fg + c * check_f)
        c_new = i * ig + c * fg
        og = jax.nn.sigmoid(a_og + c_new * check_o)
        h_new = og * jnp.tanh(c_new)
        m = m_t[:, None]
        h_next = jnp.where(m > 0, h_new, h)
        c_next = jnp.where(m > 0, c_new, c)
        return (h_next, c_next), h_new * m

    (hT, cT), ys = lax.scan(step, (h0, c0), (xs, mask))
    return ys, hT, cT


# ---------------------------------------------------------------- pallas fwd

def _lstm_kernel(with_residuals, xs_ref, mask_ref, w_ref, pI_ref, pF_ref,
                 pO_ref, h0_ref, c0_ref, *refs):
    if with_residuals:
        ys_ref, hs_ref, cs_ref, gates_ref, h_s, c_s = refs
    else:
        ys_ref, hT_ref, cT_ref, h_s, c_s = refs
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        h_s[:] = h0_ref[:]
        c_s[:] = c0_ref[:]

    h = h_s[:]
    c = c_s[:]
    H = c.shape[-1]
    m = mask_ref[0]  # [B, 1] (mask is fed as [T, B, 1] for tiling rules)
    gates = xs_ref[0] + jnp.dot(h, w_ref[:],
                                preferred_element_type=jnp.float32
                                ).astype(h.dtype)
    a_i = gates[:, :H]
    a_ig = gates[:, H:2 * H]
    a_fg = gates[:, 2 * H:3 * H]
    a_og = gates[:, 3 * H:]
    i = jnp.tanh(a_i)
    ig = jax.nn.sigmoid(a_ig + c * pI_ref[0])
    fg = jax.nn.sigmoid(a_fg + c * pF_ref[0])
    c_new = i * ig + c * fg
    og = jax.nn.sigmoid(a_og + c_new * pO_ref[0])
    h_new = og * jnp.tanh(c_new)

    h_next = jnp.where(m > 0, h_new, h)
    c_next = jnp.where(m > 0, c_new, c)
    h_s[:] = h_next
    c_s[:] = c_next
    ys_ref[0] = h_new * m
    if with_residuals:
        hs_ref[0] = h_next
        cs_ref[0] = c_next
        gates_ref[0] = jnp.concatenate([i, ig, fg, og], axis=-1)
    else:
        # final-state outputs use a constant index map; the last grid step's
        # write is what the caller sees
        hT_ref[:] = h_next
        cT_ref[:] = c_next


def _lstm_pallas(xs, mask, w, pI, pF, pO, h0, c0, with_residuals):
    T, B, H4 = xs.shape
    H = H4 // 4
    dt = xs.dtype
    t_block = lambda *shape: pl.BlockSpec(
        (1,) + shape, lambda t: (t,) + (0,) * len(shape),
        memory_space=pltpu.VMEM)
    full = lambda *shape: pl.BlockSpec(
        shape, lambda t: (0,) * len(shape), memory_space=pltpu.VMEM)
    if with_residuals:
        out_shapes = (
            jax.ShapeDtypeStruct((T, B, H), dt),       # ys
            jax.ShapeDtypeStruct((T, B, H), dt),       # hs (guarded chain)
            jax.ShapeDtypeStruct((T, B, H), dt),       # cs (guarded chain)
            jax.ShapeDtypeStruct((T, B, 4 * H), dt),   # activated gates
        )
        out_specs = (t_block(B, H), t_block(B, H), t_block(B, H),
                     t_block(B, 4 * H))
    else:
        out_shapes = (
            jax.ShapeDtypeStruct((T, B, H), dt),       # ys
            jax.ShapeDtypeStruct((B, H), dt),          # hT
            jax.ShapeDtypeStruct((B, H), dt),          # cT
        )
        out_specs = (t_block(B, H), full(B, H), full(B, H))
    return pl.pallas_call(
        functools.partial(_lstm_kernel, with_residuals),
        grid=(T,),
        in_specs=[
            t_block(B, 4 * H),            # xs
            t_block(B, 1),                # mask as [T, B, 1]
            full(H, 4 * H),               # w
            full(1, H), full(1, H), full(1, H),   # peepholes
            full(B, H), full(B, H),       # h0, c0
        ],
        out_specs=out_specs,
        out_shape=out_shapes,
        scratch_shapes=[pltpu.VMEM((B, H), dt), pltpu.VMEM((B, H), dt)],
        interpret=common.interpret(),
    )(xs, mask[..., None], w, pI.reshape(1, H), pF.reshape(1, H),
      pO.reshape(1, H), h0, c0)


# ------------------------------------------------- pallas fwd, tiled-H
# For big hidden sizes (BASELINE.md h=1280: w alone is 26 MB fp32) the
# weight cannot stay VMEM-resident. This variant tiles the HIDDEN
# dimension: grid (T, J) with J = H/Hb column blocks iterated innermost;
# block (t, j) streams w[:, 4 gate columns of block j] from HBM, computes
# that block's gates/cell update, and keeps only the full h/c state
# (2*B*H) resident in scratch. The cell math is elementwise in the H
# columns, so blocks are independent within a timestep; the sequential
# TPU grid guarantees every j of step t completes before step t+1 reads
# the full h.

def _lstm_kernel_tiled(with_residuals, hb, xs_ref, mask_ref, w_ref, pI_ref,
                       pF_ref, pO_ref, h0_ref, c0_ref, *refs):
    if with_residuals:
        ys_ref, hs_ref, cs_ref, gates_ref, h_s, hn_s, c_s = refs
    else:
        ys_ref, hT_ref, cT_ref, h_s, hn_s, c_s = refs
    t = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(jnp.logical_and(t == 0, j == 0))
    def _():
        h_s[:] = h0_ref[:]
        c_s[:] = c0_ref[:]

    cols = pl.dslice(j * hb, hb)
    # every j block of this timestep must see the SAME h_{t-1}: h_s holds
    # the previous step all timestep long; new values buffer in hn_s and
    # commit after the last block
    h = h_s[:]                      # full [B, H] = h_{t-1}
    c = c_s[:, cols]                # [B, hb]
    m = mask_ref[0]                 # [B, 1]
    B = h.shape[0]
    H = h.shape[1]
    # w block [H, 4, hb] -> [H, 4*hb] (minor-axes merge, layout no-op)
    wb = w_ref[:].reshape(H, 4 * hb)
    gates = (xs_ref[0].reshape(B, 4 * hb)
             + jnp.dot(h, wb, preferred_element_type=jnp.float32
                       ).astype(h.dtype)).reshape(B, 4, hb)
    a_i, a_ig, a_fg, a_og = (gates[:, 0], gates[:, 1], gates[:, 2],
                             gates[:, 3])
    i = jnp.tanh(a_i)
    ig = jax.nn.sigmoid(a_ig + c * pI_ref[0])
    fg = jax.nn.sigmoid(a_fg + c * pF_ref[0])
    c_new = i * ig + c * fg
    og = jax.nn.sigmoid(a_og + c_new * pO_ref[0])
    h_new = og * jnp.tanh(c_new)

    h_prev = h_s[:, cols]
    h_next = jnp.where(m > 0, h_new, h_prev)
    c_next = jnp.where(m > 0, c_new, c)
    hn_s[:, cols] = h_next
    c_s[:, cols] = c_next

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        h_s[:] = hn_s[:]

    ys_ref[0] = h_new * m
    if with_residuals:
        hs_ref[0] = h_next
        cs_ref[0] = c_next
        gates_ref[0] = jnp.stack([i, ig, fg, og], axis=1)
    else:
        hT_ref[:] = h_next
        cT_ref[:] = c_next


def _pick_hblock(H: int, B: int, itemsize: int) -> int:
    """Largest lane-aligned divisor of H whose per-block working set
    (streamed weight block + step blocks + full state) fits the VMEM
    budget; 0 if none."""
    for hb in (1024, 512, 256, 128):
        if H % hb:
            continue
        resident = itemsize * (
            H * 4 * hb        # weight block
            + 6 * B * 4 * hb  # xs/gates/ys blocks (double-buffered)
            + 3 * B * H       # h (prev + commit buffer) / c scratch
            + 4 * B * hb)     # residual blocks
        if resident <= common.VMEM_BUDGET_BYTES:
            return hb
    return 0


def _lstm_pallas_tiled(xs, mask, w, pI, pF, pO, h0, c0, with_residuals,
                       hb):
    T, B, H4 = xs.shape
    H = H4 // 4
    J = H // hb
    dt = xs.dtype
    xs4 = xs.reshape(T, B, 4, H)
    w4 = w.reshape(H, 4, H)
    if with_residuals:
        out_shapes = (
            jax.ShapeDtypeStruct((T, B, H), dt),
            jax.ShapeDtypeStruct((T, B, H), dt),
            jax.ShapeDtypeStruct((T, B, H), dt),
            jax.ShapeDtypeStruct((T, B, 4, H), dt),
        )
        out_specs = (
            pl.BlockSpec((1, B, hb), lambda t, j: (t, 0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, B, hb), lambda t, j: (t, 0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, B, hb), lambda t, j: (t, 0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, B, 4, hb), lambda t, j: (t, 0, 0, j),
                         memory_space=pltpu.VMEM),
        )
    else:
        out_shapes = (
            jax.ShapeDtypeStruct((T, B, H), dt),
            jax.ShapeDtypeStruct((B, H), dt),
            jax.ShapeDtypeStruct((B, H), dt),
        )
        out_specs = (
            pl.BlockSpec((1, B, hb), lambda t, j: (t, 0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((B, hb), lambda t, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((B, hb), lambda t, j: (0, j),
                         memory_space=pltpu.VMEM),
        )
    res = pl.pallas_call(
        functools.partial(_lstm_kernel_tiled, with_residuals, hb),
        grid=(T, J),
        in_specs=[
            pl.BlockSpec((1, B, 4, hb), lambda t, j: (t, 0, 0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, B, 1), lambda t, j: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((H, 4, hb), lambda t, j: (0, 0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, hb), lambda t, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, hb), lambda t, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, hb), lambda t, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((B, H), lambda t, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((B, H), lambda t, j: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=out_specs,
        out_shape=out_shapes,
        scratch_shapes=[pltpu.VMEM((B, H), dt), pltpu.VMEM((B, H), dt),
                        pltpu.VMEM((B, H), dt)],
        interpret=common.interpret(),
    )(xs4, mask[..., None], w4, pI.reshape(1, H), pF.reshape(1, H),
      pO.reshape(1, H), h0, c0)
    if with_residuals:
        ys, hs, cs, gates4 = res
        return ys, hs, cs, gates4.reshape(T, B, 4 * H)
    return res


# ------------------------------------------------------------- custom vjp

@jax.custom_vjp
def _lstm_core(xs, mask, w, pI, pF, pO, h0, c0):
    # primal-only path (inference): lean kernel without backward residuals
    ys, hT, cT = _lstm_pallas(xs, mask, w, pI, pF, pO, h0, c0,
                              with_residuals=False)
    return ys, hT, cT


def _fwd_rule(xs, mask, w, pI, pF, pO, h0, c0):
    ys, hs, cs, gates = _lstm_pallas(xs, mask, w, pI, pF, pO, h0, c0,
                                     with_residuals=True)
    res = (mask, w, pI, pF, pO, h0, c0, hs, cs, gates)
    return (ys, hs[-1], cs[-1]), res


def _hb_of(xs):
    T, B, H4 = xs.shape
    return _pick_hblock(H4 // 4, B, jnp.dtype(xs.dtype).itemsize)


@jax.custom_vjp
def _lstm_core_tiled(xs, mask, w, pI, pF, pO, h0, c0):
    ys, hT, cT = _lstm_pallas_tiled(xs, mask, w, pI, pF, pO, h0, c0,
                                    with_residuals=False, hb=_hb_of(xs))
    return ys, hT, cT


def _fwd_rule_tiled(xs, mask, w, pI, pF, pO, h0, c0):
    ys, hs, cs, gates = _lstm_pallas_tiled(
        xs, mask, w, pI, pF, pO, h0, c0, with_residuals=True,
        hb=_hb_of(xs))
    res = (mask, w, pI, pF, pO, h0, c0, hs, cs, gates)
    return (ys, hs[-1], cs[-1]), res


def _bwd_rule(res, grads):
    dys, dhT, dcT = grads
    mask, w, pI, pF, pO, h0, c0, hs, cs, gates = res
    T, B, H = hs.shape
    # previous-state chains (guarded): h_prev[t] = hs[t-1] (h0 at t=0)
    h_prev = jnp.concatenate([h0[None], hs[:-1]], axis=0)
    c_prev = jnp.concatenate([c0[None], cs[:-1]], axis=0)

    def step(carry, inp):
        dh, dc, dW, dpI, dpF, dpO = carry
        dy_t, m_t, g_t, c_new, c_pv, h_pv = inp
        m = m_t[:, None]
        i = g_t[:, :H]
        ig = g_t[:, H:2 * H]
        fg = g_t[:, 2 * H:3 * H]
        og = g_t[:, 3 * H:]
        dh_new = m * (dh + dy_t)
        dc_new = m * dc
        tc = jnp.tanh(c_new)
        da_og = (dh_new * tc) * og * (1 - og)
        dc_tot = dc_new + dh_new * og * (1 - tc * tc) + da_og * pO
        da_i = dc_tot * ig * (1 - i * i)
        da_ig = (dc_tot * i) * ig * (1 - ig)
        da_fg = (dc_tot * c_pv) * fg * (1 - fg)
        dc_prev = (1 - m) * dc + dc_tot * fg + da_ig * pI + da_fg * pF
        dgates = jnp.concatenate([da_i, da_ig, da_fg, da_og], axis=-1)
        dh_prev = (1 - m) * dh + dgates @ w.T
        dW = dW + h_pv.T @ dgates
        dpI = dpI + jnp.sum(da_ig * c_pv, axis=0)
        dpF = dpF + jnp.sum(da_fg * c_pv, axis=0)
        dpO = dpO + jnp.sum(da_og * c_new, axis=0)
        return (dh_prev, dc_prev, dW, dpI, dpF, dpO), dgates

    zW = jnp.zeros_like(w)
    zH = jnp.zeros_like(pI)
    (dh0, dc0, dW, dpI, dpF, dpO), dxs = lax.scan(
        step, (dhT, dcT, zW, zH, zH, zH),
        (dys, mask, gates, cs, c_prev, h_prev), reverse=True)
    return dxs, None, dW, dpI, dpF, dpO, dh0, dc0


_lstm_core.defvjp(_fwd_rule, _bwd_rule)
_lstm_core_tiled.defvjp(_fwd_rule_tiled, _bwd_rule)


# ---------------------------------------------------------------- public

def lstm_dispatch(B: int, H: int, itemsize: int = 4) -> str:
    """Which implementation these shapes take: "resident" (weight stays
    in VMEM all T steps), "tiled" (big hidden sizes stream gate-column
    blocks — BASELINE.md h=1280), or "ref" (lax.scan). Exposed so tests
    can pin the benchmark shapes to their intended path."""
    if common.mode() == "ref":
        return "ref"
    resident = itemsize * (H * 4 * H + 6 * B * 4 * H + 4 * B * H)
    if resident <= common.VMEM_BUDGET_BYTES:
        return "resident"
    if H % 128 == 0 and _pick_hblock(H, B, itemsize):
        return "tiled"
    return "ref"


BENCH_SHAPES = [(64, 256), (64, 512), (64, 1280), (128, 256), (128, 1280),
                (256, 256), (256, 1280), (512, 512)]


def kernel_dispatch_table():
    """{"lstm_bs{B}_h{H}": path} for every BASELINE.md rnn-table shape
    (benchmark/README.md:108-161). bench.py embeds this in its output so
    perf claims and dispatch can never drift apart silently."""
    return {f"lstm_bs{b}_h{h}": lstm_dispatch(b, h)
            for b, h in BENCH_SHAPES}


def lstm_sequence(xs, mask, w, gate_bias, check_i, check_f, check_o, h0, c0,
                  reverse=False):
    """Fused LSTM over a padded [T,B,4H] gate-projection sequence.

    Dispatch (``lstm_dispatch``): the resident Pallas kernel when the
    recurrent weight fits VMEM for all T steps, the tiled Pallas kernel
    (weight streamed in gate-column blocks) for big hidden sizes, else
    the lax.scan reference. ``reverse=True`` runs the recurrence
    back-to-front (outputs stay in input time order). Returns
    (ys [T,B,H], hT, cT). Differentiable on every path.
    """
    if reverse:
        ys, hT, cT = lstm_sequence(jnp.flip(xs, 0), jnp.flip(mask, 0), w,
                                   gate_bias, check_i, check_f, check_o,
                                   h0, c0)
        return jnp.flip(ys, 0), hT, cT
    T, B, H4 = xs.shape
    H = H4 // 4
    path = lstm_dispatch(B, H, jnp.dtype(xs.dtype).itemsize)
    if path == "ref":
        return lstm_sequence_ref(xs, mask, w, gate_bias, check_i, check_f,
                                 check_o, h0, c0)
    xs_b = xs + gate_bias  # fold bias into the pre-projected input once
    core = _lstm_core if path == "resident" else _lstm_core_tiled
    return core(xs_b, mask, w, check_i, check_f, check_o, h0, c0)
