"""Fused LSTM sequence kernel (Pallas) with analytic backward.

TPU-native equivalent of the reference's fused LSTM cell kernels
(`paddle/cuda/include/hl_gpu_lstm.cuh:46-67`, driven per-timestep by
`LstmLayer.cpp`): the whole recurrence runs as ONE Pallas kernel — the grid
iterates time (TPU grids execute sequentially), the recurrent weight stays
resident in VMEM across all T steps, and each step fuses the [B,H]x[H,4H]
recurrent matmul (MXU) with the gate nonlinearities (VPU). The input
projection x·W_in (the big MXU matmul) happens outside, batched over all
timesteps, exactly as the reference splits `Layer::forward` projection from
the fused cell.

Cell math (reference gate order [input, input_gate, forget_gate,
output_gate], peephole diagonals checkI/F/O):

    i  = tanh(a_i)
    ig = sigmoid(a_ig + c_prev * pI)
    fg = sigmoid(a_fg + c_prev * pF)
    c  = i*ig + c_prev*fg
    og = sigmoid(a_og + c * pO)
    h  = og * tanh(c)

Padded timesteps (mask==0) hold the carried state; outputs are zeroed —
this preserves the reference's ragged-sequence semantics
(`Argument.sequenceStartPositions`) in a static-shape layout.

Backward is an analytic reverse-time `lax.scan` over residuals saved by the
forward kernel (activated gates + state chains), mirroring the cuDNN-style
"save gates, no recompute" strategy.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.ops import common


def lstm_sequence_ref(xs, mask, w, gate_bias, check_i, check_f, check_o,
                      h0, c0):
    """Pure lax.scan reference. xs [T,B,4H] (pre-projected inputs), mask
    [T,B], w [H,4H]. Returns (ys [T,B,H], hT, cT)."""
    H = h0.shape[-1]

    def step(carry, inp):
        h, c = carry
        x_t, m_t = inp
        gates = x_t + h @ w + gate_bias
        a_i, a_ig, a_fg, a_og = jnp.split(gates, 4, axis=-1)
        i = jnp.tanh(a_i)
        ig = jax.nn.sigmoid(a_ig + c * check_i)
        fg = jax.nn.sigmoid(a_fg + c * check_f)
        c_new = i * ig + c * fg
        og = jax.nn.sigmoid(a_og + c_new * check_o)
        h_new = og * jnp.tanh(c_new)
        m = m_t[:, None]
        h_next = jnp.where(m > 0, h_new, h)
        c_next = jnp.where(m > 0, c_new, c)
        return (h_next, c_next), h_new * m

    (hT, cT), ys = lax.scan(step, (h0, c0), (xs, mask))
    return ys, hT, cT


# ---------------------------------------------------------------- pallas fwd

def _lstm_kernel(with_residuals, xs_ref, mask_ref, w_ref, pI_ref, pF_ref,
                 pO_ref, h0_ref, c0_ref, *refs):
    if with_residuals:
        ys_ref, hs_ref, cs_ref, gates_ref, h_s, c_s = refs
    else:
        ys_ref, hT_ref, cT_ref, h_s, c_s = refs
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        h_s[:] = h0_ref[:]
        c_s[:] = c0_ref[:]

    h = h_s[:]
    c = c_s[:]
    H = c.shape[-1]
    m = mask_ref[0]  # [B, 1] (mask is fed as [T, B, 1] for tiling rules)
    gates = xs_ref[0] + jnp.dot(h, w_ref[:],
                                preferred_element_type=jnp.float32
                                ).astype(h.dtype)
    a_i = gates[:, :H]
    a_ig = gates[:, H:2 * H]
    a_fg = gates[:, 2 * H:3 * H]
    a_og = gates[:, 3 * H:]
    i = jnp.tanh(a_i)
    ig = jax.nn.sigmoid(a_ig + c * pI_ref[0])
    fg = jax.nn.sigmoid(a_fg + c * pF_ref[0])
    c_new = i * ig + c * fg
    og = jax.nn.sigmoid(a_og + c_new * pO_ref[0])
    h_new = og * jnp.tanh(c_new)

    h_next = jnp.where(m > 0, h_new, h)
    c_next = jnp.where(m > 0, c_new, c)
    h_s[:] = h_next
    c_s[:] = c_next
    ys_ref[0] = h_new * m
    if with_residuals:
        hs_ref[0] = h_next
        cs_ref[0] = c_next
        gates_ref[0] = jnp.concatenate([i, ig, fg, og], axis=-1)
    else:
        # final-state outputs use a constant index map; the last grid step's
        # write is what the caller sees
        hT_ref[:] = h_next
        cT_ref[:] = c_next


def _lstm_pallas(xs, mask, w, pI, pF, pO, h0, c0, with_residuals):
    T, B, H4 = xs.shape
    H = H4 // 4
    dt = xs.dtype
    t_block = lambda *shape: pl.BlockSpec(
        (1,) + shape, lambda t: (t,) + (0,) * len(shape),
        memory_space=pltpu.VMEM)
    full = lambda *shape: pl.BlockSpec(
        shape, lambda t: (0,) * len(shape), memory_space=pltpu.VMEM)
    if with_residuals:
        out_shapes = (
            jax.ShapeDtypeStruct((T, B, H), dt),       # ys
            jax.ShapeDtypeStruct((T, B, H), dt),       # hs (guarded chain)
            jax.ShapeDtypeStruct((T, B, H), dt),       # cs (guarded chain)
            jax.ShapeDtypeStruct((T, B, 4 * H), dt),   # activated gates
        )
        out_specs = (t_block(B, H), t_block(B, H), t_block(B, H),
                     t_block(B, 4 * H))
    else:
        out_shapes = (
            jax.ShapeDtypeStruct((T, B, H), dt),       # ys
            jax.ShapeDtypeStruct((B, H), dt),          # hT
            jax.ShapeDtypeStruct((B, H), dt),          # cT
        )
        out_specs = (t_block(B, H), full(B, H), full(B, H))
    return pl.pallas_call(
        functools.partial(_lstm_kernel, with_residuals),
        grid=(T,),
        in_specs=[
            t_block(B, 4 * H),            # xs
            t_block(B, 1),                # mask as [T, B, 1]
            full(H, 4 * H),               # w
            full(1, H), full(1, H), full(1, H),   # peepholes
            full(B, H), full(B, H),       # h0, c0
        ],
        out_specs=out_specs,
        out_shape=out_shapes,
        scratch_shapes=[pltpu.VMEM((B, H), dt), pltpu.VMEM((B, H), dt)],
        interpret=common.interpret(),
    )(xs, mask[..., None], w, pI.reshape(1, H), pF.reshape(1, H),
      pO.reshape(1, H), h0, c0)


# ------------------------------------------------------------- custom vjp

@jax.custom_vjp
def _lstm_core(xs, mask, w, pI, pF, pO, h0, c0):
    # primal-only path (inference): lean kernel without backward residuals
    ys, hT, cT = _lstm_pallas(xs, mask, w, pI, pF, pO, h0, c0,
                              with_residuals=False)
    return ys, hT, cT


def _fwd_rule(xs, mask, w, pI, pF, pO, h0, c0):
    ys, hs, cs, gates = _lstm_pallas(xs, mask, w, pI, pF, pO, h0, c0,
                                     with_residuals=True)
    res = (mask, w, pI, pF, pO, h0, c0, hs, cs, gates)
    return (ys, hs[-1], cs[-1]), res


def _bwd_rule(res, grads):
    dys, dhT, dcT = grads
    mask, w, pI, pF, pO, h0, c0, hs, cs, gates = res
    T, B, H = hs.shape
    # previous-state chains (guarded): h_prev[t] = hs[t-1] (h0 at t=0)
    h_prev = jnp.concatenate([h0[None], hs[:-1]], axis=0)
    c_prev = jnp.concatenate([c0[None], cs[:-1]], axis=0)

    def step(carry, inp):
        dh, dc, dW, dpI, dpF, dpO = carry
        dy_t, m_t, g_t, c_new, c_pv, h_pv = inp
        m = m_t[:, None]
        i = g_t[:, :H]
        ig = g_t[:, H:2 * H]
        fg = g_t[:, 2 * H:3 * H]
        og = g_t[:, 3 * H:]
        dh_new = m * (dh + dy_t)
        dc_new = m * dc
        tc = jnp.tanh(c_new)
        da_og = (dh_new * tc) * og * (1 - og)
        dc_tot = dc_new + dh_new * og * (1 - tc * tc) + da_og * pO
        da_i = dc_tot * ig * (1 - i * i)
        da_ig = (dc_tot * i) * ig * (1 - ig)
        da_fg = (dc_tot * c_pv) * fg * (1 - fg)
        dc_prev = (1 - m) * dc + dc_tot * fg + da_ig * pI + da_fg * pF
        dgates = jnp.concatenate([da_i, da_ig, da_fg, da_og], axis=-1)
        dh_prev = (1 - m) * dh + dgates @ w.T
        dW = dW + h_pv.T @ dgates
        dpI = dpI + jnp.sum(da_ig * c_pv, axis=0)
        dpF = dpF + jnp.sum(da_fg * c_pv, axis=0)
        dpO = dpO + jnp.sum(da_og * c_new, axis=0)
        return (dh_prev, dc_prev, dW, dpI, dpF, dpO), dgates

    zW = jnp.zeros_like(w)
    zH = jnp.zeros_like(pI)
    (dh0, dc0, dW, dpI, dpF, dpO), dxs = lax.scan(
        step, (dhT, dcT, zW, zH, zH, zH),
        (dys, mask, gates, cs, c_prev, h_prev), reverse=True)
    return dxs, None, dW, dpI, dpF, dpO, dh0, dc0


_lstm_core.defvjp(_fwd_rule, _bwd_rule)


# ---------------------------------------------------------------- public

def lstm_sequence(xs, mask, w, gate_bias, check_i, check_f, check_o, h0, c0,
                  reverse=False):
    """Fused LSTM over a padded [T,B,4H] gate-projection sequence.

    Dispatches to the Pallas kernel when the resident working set (recurrent
    weight + per-step blocks) fits VMEM, else to the lax.scan reference.
    ``reverse=True`` runs the recurrence back-to-front (outputs stay in
    input time order). Returns (ys [T,B,H], hT, cT). Differentiable either
    way.
    """
    if reverse:
        ys, hT, cT = lstm_sequence(jnp.flip(xs, 0), jnp.flip(mask, 0), w,
                                   gate_bias, check_i, check_f, check_o,
                                   h0, c0)
        return jnp.flip(ys, 0), hT, cT
    T, B, H4 = xs.shape
    H = H4 // 4
    itemsize = jnp.dtype(xs.dtype).itemsize
    resident = itemsize * (H * H4 + 6 * B * H4 + 4 * B * H)
    if not common.use_pallas(resident):
        return lstm_sequence_ref(xs, mask, w, gate_bias, check_i, check_f,
                                 check_o, h0, c0)
    xs_b = xs + gate_bias  # fold bias into the pre-projected input once
    return _lstm_core(xs_b, mask, w, check_i, check_f, check_o, h0, c0)
