"""Pallas TPU kernel library — the framework's `paddle/cuda` equivalent.

The reference ships a hand-written device kernel library (`paddle/cuda`:
fused LSTM/GRU cell kernels `hl_gpu_lstm.cuh` / `hl_gru_ops.cuh`, sequence
scatter/gather `hl_sequence.h`, top-k `hl_top_k.h`) under the C `hl_*` API
with CPU stubs so GPU-less builds still run.  Here the same role is played
by Pallas TPU kernels with two fallback tiers:

- on TPU: the Pallas kernel (compiled by Mosaic, data staged through VMEM);
- elsewhere (CPU test meshes): either the kernel under ``interpret=True``
  or a pure ``lax.scan``/``jnp`` reference — the reference implementations
  are also the ground truth the kernels are unit-tested against.

Selection is automatic (see ``common.use_pallas``); nothing else in the
framework needs to know which tier ran.
"""

from paddle_tpu.ops.common import use_pallas, force_mode
from paddle_tpu.ops.lstm import lstm_sequence, lstm_sequence_ref
from paddle_tpu.ops.gru import gru_sequence, gru_sequence_ref
from paddle_tpu.ops.attention import (blockwise_attention, flash_attention,
                                      mha_reference)
from paddle_tpu.ops.crf import crf_log_z, crf_log_z_ref
from paddle_tpu.ops.ctc import ctc_ll, ctc_ll_ref

__all__ = [
    "use_pallas", "force_mode",
    "lstm_sequence", "lstm_sequence_ref",
    "gru_sequence", "gru_sequence_ref",
    "blockwise_attention", "flash_attention", "mha_reference",
    "crf_log_z", "crf_log_z_ref",
    "ctc_ll", "ctc_ll_ref",
]
