"""Kernel-dispatch policy: pallas-compiled / pallas-interpret / reference.

Mirrors the role of the reference's CPU stub layer
(`paddle/cuda/include/stub/*_stub.h`): every kernel has a reference
implementation that runs anywhere, and the fast path is selected by the
platform actually present.
"""

from __future__ import annotations

import contextlib
import os
from typing import Optional

import jax

# None = auto; "pallas" = force compiled; "interpret" = force interpreter;
# "ref" = force pure-JAX reference implementation.
_FORCED: Optional[str] = os.environ.get("PADDLE_TPU_KERNELS") or None

# VMEM budget used to decide whether a kernel's resident working set
# (weights + a few time-step blocks) fits on-chip; conservative vs ~16MB.
VMEM_BUDGET_BYTES = 12 * 1024 * 1024


@contextlib.contextmanager
def force_mode(mode: Optional[str]):
    """Force kernel dispatch for a scope (tests use "interpret"/"ref")."""
    global _FORCED
    prev, _FORCED = _FORCED, mode
    try:
        yield
    finally:
        _FORCED = prev


def mode() -> str:
    if _FORCED is not None:
        return _FORCED
    return "pallas" if jax.default_backend() == "tpu" else "ref"


def use_pallas(resident_bytes: int = 0) -> bool:
    """Should this op take the Pallas path (compiled or interpreted)?"""
    m = mode()
    if m == "ref":
        return False
    if resident_bytes > VMEM_BUDGET_BYTES:
        return False
    return True


def interpret() -> bool:
    return mode() == "interpret"


# shared kernel-layout vocabulary -------------------------------------------

NEG = -1e30     # finite -inf stand-in (log-space padding)
LANE = 128      # TPU vector lane width; minor axes pad to a multiple


def time_block(*shape):
    """BlockSpec for a [T, ...]-shaped operand consumed one step per grid
    index (the sequential-time pattern every fused recurrence uses)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    return pl.BlockSpec((1,) + shape, lambda t: (t,) + (0,) * len(shape),
                        memory_space=pltpu.VMEM)


def resident_block(*shape):
    """BlockSpec for an operand resident in VMEM across all grid steps."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    return pl.BlockSpec(shape, lambda t: (0,) * len(shape),
                        memory_space=pltpu.VMEM)
