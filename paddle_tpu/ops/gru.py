"""Fused GRU sequence kernel (Pallas) with analytic backward.

TPU-native equivalent of the reference's fused GRU cell kernels
(`paddle/cuda/include/hl_gru_ops.cuh:28-81`, driven by `GruLayer.cpp`).
Same design as ops/lstm.py: the grid iterates time sequentially, both
recurrent weights stay resident in VMEM, each step fuses the two recurrent
matmuls with the gate math.

Cell math (reference gate order [update z, reset r, candidate c]):

    z = sigmoid(x_z + h·Wg_z)        Wg = [H, 2H] for (z, r)
    r = sigmoid(x_r + h·Wg_r)
    c = tanh(x_c + (r*h)·Ws)         Ws = [H, H]
    h' = (1-z)*h + z*c

Mask semantics identical to ops/lstm.py (state held through padding,
outputs zeroed).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.ops import common


def gru_sequence_ref(xs, mask, w_gate, w_state, bias, h0):
    """Pure lax.scan reference. xs [T,B,3H], mask [T,B], w_gate [H,2H],
    w_state [H,H], bias [3H]. Returns (ys [T,B,H], hT)."""
    H = h0.shape[-1]

    def step(carry, inp):
        h = carry
        x_t, m_t = inp
        x_t = x_t + bias
        zr = x_t[:, :2 * H] + h @ w_gate
        z = jax.nn.sigmoid(zr[:, :H])
        r = jax.nn.sigmoid(zr[:, H:])
        c = jnp.tanh(x_t[:, 2 * H:] + (r * h) @ w_state)
        h_new = h - z * h + z * c
        m = m_t[:, None]
        h_next = jnp.where(m > 0, h_new, h)
        return h_next, h_new * m

    hT, ys = lax.scan(step, h0, (xs, mask))
    return ys, hT


# ---------------------------------------------------------------- pallas fwd

def _gru_kernel(with_residuals, xs_ref, mask_ref, wg_ref, ws_ref, h0_ref,
                *refs):
    if with_residuals:
        ys_ref, hs_ref, gates_ref, h_s = refs
    else:
        ys_ref, hT_ref, h_s = refs
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        h_s[:] = h0_ref[:]

    h = h_s[:]
    H = h.shape[-1]
    x = xs_ref[0]
    zr = x[:, :2 * H] + jnp.dot(h, wg_ref[:],
                                preferred_element_type=jnp.float32
                                ).astype(h.dtype)
    z = jax.nn.sigmoid(zr[:, :H])
    r = jax.nn.sigmoid(zr[:, H:])
    c = jnp.tanh(x[:, 2 * H:] + jnp.dot(
        r * h, ws_ref[:], preferred_element_type=jnp.float32).astype(h.dtype))
    h_new = h - z * h + z * c
    m = mask_ref[0]  # [B, 1] (mask fed as [T, B, 1] for tiling rules)
    h_next = jnp.where(m > 0, h_new, h)
    h_s[:] = h_next
    ys_ref[0] = h_new * m
    if with_residuals:
        hs_ref[0] = h_next
        gates_ref[0] = jnp.concatenate([z, r, c], axis=-1)
    else:
        hT_ref[:] = h_next


def _gru_pallas(xs, mask, w_gate, w_state, h0, with_residuals):
    T, B, H3 = xs.shape
    H = H3 // 3
    dt = xs.dtype
    t_block = lambda *shape: pl.BlockSpec(
        (1,) + shape, lambda t: (t,) + (0,) * len(shape),
        memory_space=pltpu.VMEM)
    full = lambda *shape: pl.BlockSpec(
        shape, lambda t: (0,) * len(shape), memory_space=pltpu.VMEM)
    if with_residuals:
        out_specs = (t_block(B, H), t_block(B, H), t_block(B, 3 * H))
        out_shape = (jax.ShapeDtypeStruct((T, B, H), dt),
                     jax.ShapeDtypeStruct((T, B, H), dt),
                     jax.ShapeDtypeStruct((T, B, 3 * H), dt))
    else:
        out_specs = (t_block(B, H), full(B, H))
        out_shape = (jax.ShapeDtypeStruct((T, B, H), dt),
                     jax.ShapeDtypeStruct((B, H), dt))
    return pl.pallas_call(
        functools.partial(_gru_kernel, with_residuals),
        grid=(T,),
        in_specs=[t_block(B, 3 * H), t_block(B, 1), full(H, 2 * H),
                  full(H, H), full(B, H)],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((B, H), dt)],
        interpret=common.interpret(),
    )(xs, mask[..., None], w_gate, w_state, h0)


# ------------------------------------------------------------- custom vjp

@jax.custom_vjp
def _gru_core(xs, mask, w_gate, w_state, h0):
    # primal-only path (inference): lean kernel without backward residuals
    return _gru_pallas(xs, mask, w_gate, w_state, h0, with_residuals=False)


def _fwd_rule(xs, mask, w_gate, w_state, h0):
    ys, hs, gates = _gru_pallas(xs, mask, w_gate, w_state, h0,
                                with_residuals=True)
    return (ys, hs[-1]), (mask, w_gate, w_state, h0, hs, gates)


def _bwd_rule(res, grads):
    dys, dhT = grads
    mask, w_gate, w_state, h0, hs, gates = res
    T, B, H = hs.shape
    h_prev = jnp.concatenate([h0[None], hs[:-1]], axis=0)

    def step(carry, inp):
        dh, dWg, dWs = carry
        dy_t, m_t, g_t, h_pv = inp
        m = m_t[:, None]
        z = g_t[:, :H]
        r = g_t[:, H:2 * H]
        c = g_t[:, 2 * H:]
        dh_new = m * (dh + dy_t)
        dz = dh_new * (c - h_pv)
        da_c = (dh_new * z) * (1 - c * c)
        drh = da_c @ w_state.T
        dr = drh * h_pv
        da_z = dz * z * (1 - z)
        da_r = dr * r * (1 - r)
        da_zr = jnp.concatenate([da_z, da_r], axis=-1)
        dh_prev = ((1 - m) * dh + dh_new * (1 - z) + drh * r
                   + da_zr @ w_gate.T)
        dWg = dWg + h_pv.T @ da_zr
        dWs = dWs + (r * h_pv).T @ da_c
        dxs_t = jnp.concatenate([da_z, da_r, da_c], axis=-1)
        return (dh_prev, dWg, dWs), dxs_t

    (dh0, dWg, dWs), dxs = lax.scan(
        step, (dhT, jnp.zeros_like(w_gate), jnp.zeros_like(w_state)),
        (dys, mask, gates, h_prev), reverse=True)
    return dxs, None, dWg, dWs, dh0


_gru_core.defvjp(_fwd_rule, _bwd_rule)


# ---------------------------------------------------------------- public

def gru_sequence(xs, mask, w_gate, w_state, bias, h0, reverse=False):
    """Fused GRU over a padded [T,B,3H] gate-projection sequence.
    ``reverse=True`` runs back-to-front (outputs stay in input time order).
    Returns (ys [T,B,H], hT). Differentiable either way."""
    if reverse:
        ys, hT = gru_sequence(jnp.flip(xs, 0), jnp.flip(mask, 0), w_gate,
                              w_state, bias, h0)
        return jnp.flip(ys, 0), hT
    T, B, H3 = xs.shape
    H = H3 // 3
    itemsize = jnp.dtype(xs.dtype).itemsize
    resident = itemsize * (3 * H * H + 6 * B * H3)
    if not common.use_pallas(resident):
        # Big hidden sizes fall back to the scan reference. Unlike the
        # LSTM (ops/lstm.py:_lstm_pallas_tiled), a gate-column-tiled GRU
        # needs two phases per timestep (the candidate matmul consumes
        # the FULL reset gate), doubling weight streaming — measured
        # benefit over XLA's scan is not established, and no BASELINE
        # benchmark shape exceeds the resident budget for GRU.
        return gru_sequence_ref(xs, mask, w_gate, w_state, bias, h0)
    return _gru_core(xs + bias, mask, w_gate, w_state, h0)
