"""Python host for the C inference API (imported by src/capi.cc).

Holds loaded merged models and their jitted inference functions; the C
shim marshals float buffers in/out as bytes. Kept free of module-level
jax work so embedding stays cheap until the first load.
"""

from __future__ import annotations

import struct
from typing import Dict, Optional

_models: Dict[int, dict] = {}
_next_id = 0


def load(path: str) -> int:
    """Load a merged model; returns a handle."""
    global _next_id
    import jax.numpy as jnp

    from paddle_tpu.core.network import Network
    from paddle_tpu.trainer.merge_model import load_merged

    graph, params, outputs = load_merged(path)
    net = Network(graph, outputs=outputs)
    data_layers = [name for name, ld in graph.layers.items()
                   if ld.type == "data"]
    mid = _next_id
    _next_id += 1
    _models[mid] = {
        "net": net,
        "params": {k: jnp.asarray(v) for k, v in params.items()},
        "outputs": outputs,
        "data_layers": data_layers,
    }
    return mid


def infer_raw(mid: int, input_name: Optional[str], payload: bytes,
              batch: int, dim: int):
    """float32 little-endian (batch, dim) buffer -> (bytes, rows, cols)
    of the first output."""
    import numpy as np

    from paddle_tpu.core.argument import Argument
    import jax.numpy as jnp

    m = _models[mid]
    if input_name is None:
        input_name = m["data_layers"][0]
    x = np.frombuffer(payload, dtype="<f4").reshape(batch, dim)
    feed = {input_name: Argument(value=jnp.asarray(x))}
    out = m["net"].apply(m["params"], feed, train=False)
    val = np.asarray(out[m["outputs"][0]].value, dtype="<f4")
    if val.ndim == 1:
        val = val[:, None]
    return val.tobytes(), int(val.shape[0]), int(val.shape[1])


def release(mid: int):
    _models.pop(mid, None)
