// C inference API implementation: embeds CPython and drives
// paddle_tpu.capi.host (which holds the jitted inference functions).
// The reference's capi runs its C++ engine in-process
// (paddle/capi/gradient_machine.h); here the engine is JAX, so the shim
// hosts the interpreter — same deployment story (link one .so, call C
// functions), TPU execution underneath.
//
// Marshalling deliberately avoids the numpy C ABI: buffers cross the
// boundary as Python bytes (PyBytes_FromStringAndSize / memcpy out).

#include <Python.h>

#include <cstring>
#include <mutex>
#include <string>

#include "../include/paddle_tpu_capi.h"

namespace {

std::mutex g_mu;
std::string g_error;
bool g_inited = false;
PyObject* g_host = nullptr;  // paddle_tpu.capi.host module

void set_error_from_python() {
  PyObject *type, *value, *trace;
  PyErr_Fetch(&type, &value, &trace);
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    const char* msg = s ? PyUnicode_AsUTF8(s) : nullptr;
    g_error = msg ? msg : "unknown python error";
    PyErr_Clear();  // PyUnicode_AsUTF8 may itself have raised
    Py_XDECREF(s);
  } else {
    g_error = "unknown error";
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(trace);
}

struct Gil {
  PyGILState_STATE st;
  Gil() : st(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(st); }
};

}  // namespace

extern "C" {

int ptc_init(const char* python_home) {
  std::lock_guard<std::mutex> lk(g_mu);
  if (g_inited) return 0;
  if (python_home != nullptr) {
    static std::wstring home;
    home.assign(python_home, python_home + strlen(python_home));
    Py_SetPythonHome(const_cast<wchar_t*>(home.c_str()));
  }
  Py_InitializeEx(0);
  g_host = PyImport_ImportModule("paddle_tpu.capi.host");
  if (g_host == nullptr) {
    set_error_from_python();
    return -1;
  }
  // release the GIL acquired by Py_Initialize so Gil{} works later
  PyEval_SaveThread();
  g_inited = true;
  return 0;
}

void* ptc_load(const char* model_path) {
  if (!g_inited) {
    g_error = "ptc_init not called";
    return nullptr;
  }
  Gil gil;
  PyObject* r = PyObject_CallMethod(g_host, "load", "s", model_path);
  if (r == nullptr) {
    set_error_from_python();
    return nullptr;
  }
  long long handle = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return reinterpret_cast<void*>(static_cast<intptr_t>(handle + 1));
}

int ptc_infer(void* model, const char* input_name, const float* data,
              int batch, int dim, float* out, int out_cap,
              int* out_rows, int* out_cols) {
  if (!g_inited) {
    g_error = "ptc_init not called";
    return -1;
  }
  Gil gil;
  long long handle =
      static_cast<long long>(reinterpret_cast<intptr_t>(model)) - 1;
  PyObject* bytes = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(data),
      static_cast<Py_ssize_t>(batch) * dim * sizeof(float));
  if (bytes == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject* r = PyObject_CallMethod(
      g_host, "infer_raw", "LzOii", handle, input_name, bytes, batch, dim);
  Py_DECREF(bytes);
  if (r == nullptr) {
    set_error_from_python();
    return -1;
  }
  // (bytes, rows, cols) — validate before converting so a misbehaving
  // host function sets g_error instead of crashing the embedder
  if (!PyTuple_Check(r) || PyTuple_Size(r) != 3 ||
      !PyBytes_Check(PyTuple_GetItem(r, 0))) {
    Py_DECREF(r);
    g_error = "infer_raw returned malformed result (want (bytes, rows, cols))";
    return -1;
  }
  PyObject* payload = PyTuple_GetItem(r, 0);
  long rows = PyLong_AsLong(PyTuple_GetItem(r, 1));
  long cols = PyLong_AsLong(PyTuple_GetItem(r, 2));
  if (PyErr_Occurred()) {
    Py_DECREF(r);
    set_error_from_python();
    return -1;
  }
  Py_ssize_t n = PyBytes_Size(payload);
  if (rows < 0 || cols < 0 ||
      static_cast<Py_ssize_t>(rows) * cols * sizeof(float) != n) {
    Py_DECREF(r);
    g_error = "infer_raw returned inconsistent rows/cols vs payload size";
    return -1;
  }
  *out_rows = static_cast<int>(rows);
  *out_cols = static_cast<int>(cols);
  if (n > static_cast<Py_ssize_t>(out_cap) * sizeof(float)) {
    Py_DECREF(r);
    g_error = "output buffer too small";
    return -2;
  }
  memcpy(out, PyBytes_AsString(payload), n);
  Py_DECREF(r);
  return 0;
}

void ptc_release(void* model) {
  if (!g_inited) return;
  Gil gil;
  long long handle =
      static_cast<long long>(reinterpret_cast<intptr_t>(model)) - 1;
  PyObject* r = PyObject_CallMethod(g_host, "release", "L", handle);
  if (r == nullptr) {
    set_error_from_python();
  }
  Py_XDECREF(r);
}

const char* ptc_last_error(void) { return g_error.c_str(); }

int ptc_shutdown(void) {
  std::lock_guard<std::mutex> lk(g_mu);
  if (!g_inited) return 0;
  PyGILState_Ensure();
  Py_XDECREF(g_host);
  g_host = nullptr;
  int rc = Py_FinalizeEx();
  g_inited = false;
  return rc;
}

}  // extern "C"
