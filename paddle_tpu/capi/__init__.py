"""C inference API (role of `paddle/capi`): see include/paddle_tpu_capi.h.

``build_library()`` compiles the shim with the host toolchain +
python3-config embed flags; returns the .so path (cached)."""

from __future__ import annotations

import os
import subprocess
import sysconfig

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "src", "capi.cc")
_SO = os.path.join(_DIR, "libpaddle_tpu_capi.so")


def _python_flags():
    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR")
    ver = sysconfig.get_config_var("LDVERSION")
    return ([f"-I{inc}"], [f"-L{libdir}", f"-lpython{ver}"], libdir)


def build_library(force: bool = False) -> str:
    if (not force and os.path.exists(_SO)
            and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)):
        return _SO
    incs, libs, libdir = _python_flags()
    cmd = (["g++", "-O2", "-shared", "-fPIC", "-std=c++17"] + incs
           + ["-o", _SO + ".tmp", _SRC] + libs
           + [f"-Wl,-rpath,{libdir}"])
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=300)
    except subprocess.CalledProcessError as e:
        raise RuntimeError(
            "capi shim build failed:\n" + e.stderr.decode(errors="replace")
        ) from e
    os.replace(_SO + ".tmp", _SO)
    return _SO
