/* C inference API (role of paddle/capi/gradient_machine.h:36-86):
 * embed the TPU inference engine in C/C++ deployments.
 *
 * Usage:
 *   ptc_init(NULL);
 *   void* m = ptc_load("model.ptmodel");          // merged model file
 *   float out[10]; int rows, cols;
 *   ptc_infer(m, NULL, input, 1, 784, out, 10, &rows, &cols);
 *   ptc_release(m); ptc_shutdown();
 *
 * All functions return 0 on success (or a handle), negative on error;
 * ptc_last_error() describes the most recent failure. Thread-safe for
 * one interpreter: calls serialize on the GIL. The engine executes on
 * whatever accelerator JAX selects (TPU when present).
 */

#ifndef PADDLE_TPU_CAPI_H
#define PADDLE_TPU_CAPI_H

#ifdef __cplusplus
extern "C" {
#endif

/* Start the embedded runtime. python_home may be NULL. */
int ptc_init(const char* python_home);

/* Load a merged model (trainer --job=merge artifact). NULL on error. */
void* ptc_load(const char* model_path);

/* Run inference: batch x dim floats for input layer `input_name`
 * (NULL = the model's first data layer). Writes up to out_cap floats,
 * sets *out_rows/*out_cols. Returns 0, or -1 (error) / -2 (out_cap too
 * small; *out_rows x *out_cols tells the needed size). */
int ptc_infer(void* model, const char* input_name, const float* data,
              int batch, int dim, float* out, int out_cap,
              int* out_rows, int* out_cols);

void ptc_release(void* model);

const char* ptc_last_error(void);

int ptc_shutdown(void);

#ifdef __cplusplus
}
#endif

#endif /* PADDLE_TPU_CAPI_H */
