from paddle_tpu.optim.optimizers import (  # noqa: F401
    Optimizer, Momentum, AdaGrad, AdaDelta, RMSProp, DecayedAdaGrad, Adam,
    Adamax, create_optimizer)
from paddle_tpu.optim.schedules import learning_rate_at  # noqa: F401
from paddle_tpu.optim.zero1 import FsdpUpdater, Zero1Updater  # noqa: F401
