"""Learning-rate schedules.

Mirrors ``paddle/parameter/LearningRateScheduler.cpp`` (created from
``OptimizationConfig.learning_rate_schedule`` with args ``decay_a``/
``decay_b``): constant, poly, caffe_poly, exp, discexp, linear. ``t`` is the
number of samples processed, as in the reference.
"""

from __future__ import annotations

import jax.numpy as jnp


def parse_manual_segments(args: str):
    """Parse ``learning_rate_args`` for the ``manual``/``pass_manual``
    schedules: ``"seg0:lr0,seg1:lr1,..."`` where segN is a cumulative
    sample (manual) or pass (pass_manual) boundary
    (``LearningRateScheduler.cpp``, SegmentsScheduler)."""
    segs = []
    for part in args.split(","):
        boundary, factor = part.split(":")
        segs.append((float(boundary), float(factor)))
    return segs


def learning_rate_at(schedule: str, lr0: float, a: float, b: float, t,
                     args: str = "", num_passes=0):
    t = jnp.asarray(t, jnp.float32)
    if schedule in ("constant", "", None):
        return jnp.asarray(lr0, jnp.float32)
    if schedule == "poly":
        return lr0 * jnp.power(1.0 + a * t, -b)
    if schedule == "caffe_poly":
        return lr0 * jnp.power(1.0 - t / a, b)
    if schedule == "exp":
        return lr0 * jnp.power(a, t / b)
    if schedule == "discexp":
        return lr0 * jnp.power(a, jnp.floor(t / b))
    if schedule == "linear":
        return jnp.maximum(lr0 - a * t, b)
    if schedule in ("manual", "pass_manual"):
        # piecewise-constant over cumulative samples (manual) or pass id
        # (pass_manual); last segment extends to infinity as in the
        # reference (SegmentsScheduler falls through to the final value).
        key = jnp.asarray(num_passes, jnp.float32) \
            if schedule == "pass_manual" else t
        segs = parse_manual_segments(args)
        lr = jnp.asarray(lr0 * segs[-1][1], jnp.float32)
        for boundary, factor in reversed(segs[:-1]):
            lr = jnp.where(key < boundary, lr0 * factor, lr)
        return lr
    raise KeyError(f"unknown learning_rate_schedule {schedule!r}")
