"""Learning-rate schedules.

Mirrors ``paddle/parameter/LearningRateScheduler.cpp`` (created from
``OptimizationConfig.learning_rate_schedule`` with args ``decay_a``/
``decay_b``): constant, poly, caffe_poly, exp, discexp, linear. ``t`` is the
number of samples processed, as in the reference.
"""

from __future__ import annotations

import jax.numpy as jnp


def learning_rate_at(schedule: str, lr0: float, a: float, b: float, t):
    t = jnp.asarray(t, jnp.float32)
    if schedule in ("constant", "", None):
        return jnp.asarray(lr0, jnp.float32)
    if schedule == "poly":
        return lr0 * jnp.power(1.0 + a * t, -b)
    if schedule == "caffe_poly":
        return lr0 * jnp.power(1.0 - t / a, b)
    if schedule == "exp":
        return lr0 * jnp.power(a, t / b)
    if schedule == "discexp":
        return lr0 * jnp.power(a, jnp.floor(t / b))
    if schedule == "linear":
        return jnp.maximum(lr0 - a * t, b)
    raise KeyError(f"unknown learning_rate_schedule {schedule!r}")
