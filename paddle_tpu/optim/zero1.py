"""ZeRO-1 sharded optimizer update (stage-1 optimizer-state partitioning).

The reference splits the parameter update across pservers so no node holds
the full optimizer state: each ``ParameterServer2`` owns a contiguous block
of every parameter, applies the optimizer to its block after
``addGradient`` (``ParameterServer2.cpp:362``), and trainers gather the
updated values. This module is that partitioning re-expressed on the mesh's
data axis (ZeRO stage 1, Rajbhandari et al.; the same scheme as
TensorFlow's parameter-server sharding):

1. every eligible parameter (and each of its optimizer slots) is viewed as
   a flat vector, zero-padded to a multiple of the data-parallel degree N,
   and reshaped to ``(N, chunk)`` — slots are STORED this way, sharded
   ``P(data)``, so each device permanently holds 1/N of every slot;
2. inside the jitted train step a ``shard_map_compat`` over the mesh
   applies ``Optimizer._update_param`` (the exact replicated code path) to
   each device's shard — XLA sees the gradient consumed shard-wise and can
   lower the backward all-reduce + slice into a reduce-scatter;
3. the updated parameter shards are all-gathered (``lax.all_gather``) back
   to full replicated arrays for the next forward pass.

The update math is elementwise per parameter for every dense optimizer, so
the sharded result is bit-exact vs the replicated path. Excluded from the
plan (they fall back to the replicated per-parameter update inside the same
``update`` call):

- static parameters (no slots at all);
- sparse lazy-path parameters (``Optimizer._is_sparse``: the per-row
  ``t_rows`` bookkeeping is row-structured, not flat-elementwise);
- parameters with a non-replicated sharding rule (e.g. embedding tables
  row-sharded over the model axis — their slots already follow the table,
  ``parallel/mesh.py:shard_opt_state``). Since r08 this is also how the
  pipeline composes: stage-stacked body parameters carry ``P(pipe, ...)``
  rules (``parallel/pipeline.py:PipelineTrainPlan.shard_rules``), so
  their slots stay 1/S-per-device on the pipe axis while the replicated
  head still partitions over the data axis here
  (``docs/pipeline_parallel.md`` interaction matrix).

Model-averaging state (``avg``) stays replicated: it is consumed whole by
``averaged_params`` at eval/save time and is rare enough not to warrant a
second layout.

Checkpoint format compatibility: ``gather_opt_state`` restores every slot
to its parameter's full shape before a save (``trainer/checkpoint.py``
stores the same keys as a replicated run), and ``pack_for_load`` reshards a
full-shape slot on restore — so resume crosses sharded<->replicated modes
in both directions.

The communication contract is machine-checked (graftlint pass 4,
``analysis/shard_audit.py``): the step's ONE fused all-gather and its
unchanged backward all-reduce are pinned in ``analysis/comm_budget.toml``
(PT501), the pack-buffer ``with_sharding_constraint`` pins below are
asserted at the jaxpr level (PT503 — removing one fails tier-1), and a
planned slot that loses its ``P(data)`` placement is PT502.

r17 generalized this module into the full-FSDP plane
(:class:`FsdpUpdater`): the same flat ``(N, chunk)`` packing applied to
the PARAMETERS themselves, partitioned over the mesh's dedicated
``fsdp`` axis with gather-on-use — each device permanently holds 1/N of
every eligible parameter and slot, the forward all-gathers each
parameter per layer, the backward reduce-scatters its gradient, and the
shard-wise update needs NO trailing gather (the next step re-gathers).
Eligibility for both updaters is ONE question asked of the canonical
layout (``parallel/layout.py:SpecLayout.fsdp_eligible``), so
model-sharded tables and pipeline stage-stacked keys are excluded by
the same rule table that places them. The fsdp programs' collectives
and per-device bytes are pinned like zero1's (``fsdp_train`` /
``fsdp_pipe`` in both budgets; the ~1/N param-bytes law is graftlint
PT602, a full-gather materialization fails PT604).
"""

from __future__ import annotations

import contextlib
import os
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.core.registry import ParamSpec
from paddle_tpu.optim.optimizers import Optimizer
from paddle_tpu.parallel import mesh as mesh_lib

# Overlap-spelling override for the FSDP gather path (r18): None = auto
# (double-buffer chain on TPU, sync spelling elsewhere — the CPU audit
# compiles must stage the exact program the budgets were pinned on);
# "force" = stage the chain regardless of backend (tests, bench A/B);
# "off" = pin the sync spelling.
_OVERLAP_FORCED: Optional[str] = os.environ.get(
    "PADDLE_TPU_FSDP_OVERLAP") or None


@contextlib.contextmanager
def overlap_spelling(mode: Optional[str]):
    """Force the FSDP gather-overlap spelling for a scope ("force" /
    "off" / None=auto). Trace-time only — it picks which program gets
    staged; re-jit after changing it."""
    global _OVERLAP_FORCED
    prev, _OVERLAP_FORCED = _OVERLAP_FORCED, mode
    try:
        yield
    finally:
        _OVERLAP_FORCED = prev


@jax.custom_vjp
def _prefetch_fence(leaf, prev_gathered):
    """``optimization_barrier`` on (next gather's input, previous
    gather's output): identity on values, but the scheduler cannot
    start gather k+1 before gather k materialises. custom_vjp because
    the primitive has no differentiation rule — and the backward we
    want is the SAME fence on the cotangents, which serializes the
    grad reduce-scatters pairwise in reverse schedule order (each one
    overlapping the previous layer's backward compute)."""
    return jax.lax.optimization_barrier((leaf, prev_gathered))


def _prefetch_fence_fwd(leaf, prev_gathered):
    return jax.lax.optimization_barrier((leaf, prev_gathered)), None


def _prefetch_fence_bwd(_, ct):
    ct_leaf, ct_prev = ct
    return jax.lax.optimization_barrier((ct_leaf, ct_prev))


_prefetch_fence.defvjp(_prefetch_fence_fwd, _prefetch_fence_bwd)


class Zero1Updater:
    """Drop-in for the ``update`` protocol of :class:`Optimizer`, with
    optimizer slots partitioned over the mesh's batch axes.

    Construct once per trainer (the plan — shapes, pad sizes, eligibility —
    is static per model); ``convert_state`` reshards an existing replicated
    state in place of a fresh ``init``.
    """

    def __init__(self, optimizer: Optimizer, mesh, params: Dict[str, Any],
                 meta: Optional[Dict[str, ParamSpec]] = None,
                 rules: Optional[Dict[str, P]] = None,
                 fsdp: bool = False):
        from paddle_tpu.parallel.layout import SpecLayout
        self.opt = optimizer
        self.mesh = mesh
        self.meta = meta or {}
        # the partition axes and sharding are THE layout's packed-role
        # derivation (SpecLayout.packed_*): the batch axes for ZeRO-1
        # (slots follow the gradient partition), the dedicated fsdp
        # axis for FsdpUpdater — one packing, two layouts, derived in
        # one place
        layout = SpecLayout(mesh, rules=rules)
        self.layout = layout
        self.axes = layout.packed_axes(fsdp=fsdp)
        self._packed_sharding = layout.packed_sharding(fsdp=fsdp)
        n = 1
        for a in self.axes:
            n *= int(dict(mesh.shape).get(a, 1))
        self.n = n
        if self.n <= 1:
            raise ValueError(
                "ZeRO-1/FSDP needs a partition degree > 1 over "
                f"{self.axes or 'the batch axes'}; with one device "
                "there is nothing to partition (callers fall back to "
                "the replicated update)")
        # plan: name -> (orig_shape, size, chunk). Only these params take
        # the sharded path; everything else falls back per-parameter.
        # Eligibility is the canonical layout's ONE question
        # (SpecLayout.fsdp_eligible): static and sparse-lazy params are
        # out, and so is anything the rule table already places —
        # model-sharded tables and pipeline stage-stacked keys follow
        # their own rule instead of the flat packing.
        self.plan: Dict[str, tuple] = {}
        self.dtypes: Dict[str, np.dtype] = {}
        for name, p in params.items():
            spec = self.meta.get(name)
            if not layout.fsdp_eligible(name, spec, optimizer):
                continue
            shape = tuple(int(d) for d in p.shape)
            size = 1
            for d in shape:
                size *= d
            chunk = -(-size // self.n)  # ceil
            self.plan[name] = (shape, size, chunk)
            self.dtypes[name] = np.dtype(p.dtype)

    # ------------------------------------------------------- layout helpers
    def _pack(self, x, name: str):
        """Full array -> zero-padded (N, chunk) view (trace-time op; free
        for replicated inputs — each device slices its own rows).

        Padding uses ``concatenate``, NOT ``jnp.pad``: on the CPU backend a
        pad op fused into the downstream elementwise update changes its
        codegen enough to round real elements differently (observed multi-
        ulp drift vs the replicated path); concatenate keeps the update
        bit-exact, which the parity tests assert."""
        _, size, chunk = self.plan[name]
        flat = x.reshape(-1)
        pad = self.n * chunk - size
        if pad:
            flat = jnp.concatenate(
                [flat, jnp.zeros((pad,), flat.dtype)])
        return flat.reshape(self.n, chunk)

    def _unpack(self, x2d, name: str):
        shape, size, _ = self.plan[name]
        return x2d.reshape(-1)[:size].reshape(shape)

    def _pack_host(self, x: np.ndarray, name: str) -> np.ndarray:
        _, size, chunk = self.plan[name]
        flat = np.asarray(x).reshape(-1)
        pad = self.n * chunk - size
        if pad:
            flat = np.concatenate([flat, np.zeros(pad, flat.dtype)])
        return flat.reshape(self.n, chunk)

    def _slot_sharding(self) -> NamedSharding:
        return self._packed_sharding

    # ------------------------------------------------------------ lifecycle
    def init(self, params, meta=None):
        """Replicated init, then shard the plan's slots."""
        return self.convert_state(self.opt.init(params, meta or self.meta))

    def convert_state(self, state):
        """Reshard a replicated optimizer state: every slot of a planned
        parameter moves to the (N, chunk) ``P(data)`` layout (including
        ``prune_mask`` — it is elementwise like the rest). Scalars and the
        ``avg`` tree stay replicated. Idempotent on already-converted
        leaves."""
        sharding = self._slot_sharding()
        new_slots = {}
        for name, slots in state["slots"].items():
            if name not in self.plan:
                new_slots[name] = slots
                continue
            _, _, chunk = self.plan[name]
            out = {}
            for slot, leaf in slots.items():
                if leaf.ndim == 2 and leaf.shape == (self.n, chunk):
                    out[slot] = jax.device_put(leaf, sharding)
                else:
                    out[slot] = jax.device_put(
                        self._pack_host(jax.device_get(leaf), name), sharding)
            new_slots[name] = out
        return {**state, "slots": new_slots}

    def gather_opt_state(self, state):
        """The checkpoint view: every planned slot back at its parameter's
        full shape (unpad + reshape), so the saved key set and array shapes
        are identical to a replicated run's — ``trainer/checkpoint.py``
        stays format-compatible and a replicated resume needs no
        conversion."""
        new_slots = {}
        for name, slots in state["slots"].items():
            if name not in self.plan:
                new_slots[name] = slots
                continue
            new_slots[name] = {slot: self._unpack(leaf, name)
                               for slot, leaf in slots.items()}
        return {**state, "slots": new_slots}

    def pack_for_load(self, key: str, value: np.ndarray, current):
        """Reshard one restored opt-state leaf (flattened key
        ``slots/<param>/<slot>``) into this plan's layout when it arrives
        at the parameter's full shape; pass-through otherwise."""
        parts = key.split("/")
        if len(parts) == 3 and parts[0] == "slots" and parts[1] in self.plan:
            if tuple(np.shape(value)) != tuple(current.shape):
                return self._pack_host(value, parts[1])
        return value

    # --------------------------------------------------------------- update
    def update(self, grads, state, params,
               meta: Optional[Dict[str, ParamSpec]] = None,
               batch_size=1, num_passes=0):
        """Same contract as :meth:`Optimizer.update`. Planned parameters
        update shard-wise under ``shard_map``; the rest run the replicated
        per-parameter body. One shared t/num_samples/lr computation keeps
        the two sub-paths on the same schedule step."""
        from paddle_tpu.optim.schedules import learning_rate_at
        opt = self.opt
        meta = meta if meta is not None else self.meta

        t = state["t"] + 1
        num_samples = state["num_samples"] + batch_size
        lr_t = learning_rate_at(
            opt.learning_rate_schedule, opt.learning_rate,
            opt.learning_rate_decay_a, opt.learning_rate_decay_b,
            num_samples, args=opt.learning_rate_args, num_passes=num_passes)
        if opt.sum_gradients:
            bsz = jnp.asarray(batch_size, jnp.float32)
            grads = {n: g * bsz for n, g in grads.items()}

        new_params = dict(params)
        new_slots = {n: s for n, s in state["slots"].items()
                     if n not in grads}
        z_names = sorted(n for n in grads
                         if n in self.plan and n in state["slots"])

        # fallback set: sparse lazy tables, model-sharded params, and any
        # grad for a param without slots — identical to Optimizer.update
        for name, g in grads.items():
            if name in z_names:
                continue
            if name not in state["slots"]:
                new_params[name] = params[name]
                continue
            spec = meta.get(name) if meta else None
            p_new, s_new = opt._update_param(
                g, params[name], state["slots"][name], spec, lr_t, t)
            new_params[name] = p_new
            new_slots[name] = s_new

        if z_names:
            # ONE fused buffer for params and grads (the ZeRO bucketing
            # trick): per-parameter (N, chunk) shards concatenate along
            # the chunk dim into a single (N, sum_chunks) array, so the
            # step issues ONE all-gather instead of one per parameter —
            # on CPU-emulated meshes per-collective dispatch dominates,
            # on TPU one large ICI transfer beats many small ones.
            offs, off = {}, 0
            for n in z_names:
                chunk = self.plan[n][2]
                offs[n] = (off, off + chunk)
                off += chunk
            # pin the fused buffers replicated: without the constraint,
            # sharding propagation lets the shard_map's P(data) demand
            # leak into the BACKWARD pass and reshape its collectives
            # (observed 2x whole-step slowdown); with it, the backward is
            # byte-identical to the replicated path's and the shard_map
            # just slices local rows
            rep = NamedSharding(self.mesh, P())
            p_fused = jax.lax.with_sharding_constraint(jnp.concatenate(
                [self._pack(params[n], n) for n in z_names], axis=1), rep)
            g_fused = jax.lax.with_sharding_constraint(jnp.concatenate(
                [self._pack(grads[n], n) for n in z_names], axis=1), rep)
            s_sh = {n: state["slots"][n] for n in z_names}
            specs = {n: (meta.get(n) if meta else None) for n in z_names}
            axes = self.axes

            def shard_update(p_loc, g_loc, s_sh, lr_t, t):
                # local view: this device's (1, sum_chunks) row of the
                # fused buffer plus its (1, chunk) slot shards. The
                # reduce-scatter of the issue lives here implicitly: the
                # gradient is consumed shard-wise, so XLA's collective
                # optimizer can fold the backward all-reduce + slice into
                # a reduce-scatter over the data axis.
                out_p, out_s = [], {}
                for n in z_names:
                    lo, hi = offs[n]
                    p1, s1 = opt._update_param(
                        g_loc[:, lo:hi], p_loc[:, lo:hi], s_sh[n],
                        specs[n], lr_t, t)
                    out_p.append(p1)
                    out_s[n] = s1
                # the ZeRO-1 all-gather: updated shards -> the full
                # replicated fused buffer for the next forward
                return jax.lax.all_gather(
                    jnp.concatenate(out_p, axis=1), axis_name=axes,
                    axis=0, tiled=True), out_s

            gathered, s_new = mesh_lib.shard_map_compat(
                shard_update, self.mesh,
                in_specs=(P(self.axes), P(self.axes), P(self.axes),
                          P(), P()),
                out_specs=(P(), P(self.axes)))(p_fused, g_fused, s_sh,
                                               lr_t, t)
            for n in z_names:
                lo, hi = offs[n]
                new_params[n] = self._unpack(gathered[:, lo:hi], n)
                new_slots[n] = s_new[n]

        new_state = {"slots": new_slots, "t": t, "num_samples": num_samples}
        if "avg" in state:
            # model averaging stays replicated (see module docstring); the
            # window semantics live in ONE place, fed by gathered params
            new_state["avg"] = opt._update_avg(state["avg"], t, new_params,
                                               new_slots)
        return new_params, new_state

    # ------------------------------------------------- delegated protocol
    def catch_up(self, params, state, meta=None, num_passes=0):
        """Sparse lazy tables are excluded from the plan, so their rows
        live replicated in the same state tree — the wrapped optimizer's
        catch-up applies unchanged."""
        return self.opt.catch_up(params, state, meta, num_passes=num_passes)

    def prune_params(self, params, state):
        return self.opt.prune_params(params, self.gather_opt_state(state))

    def averaged_params(self, state, params):
        return self.opt.averaged_params(state, params)


class FsdpUpdater(Zero1Updater):
    """Full FSDP (ZeRO stage 3): parameters AND optimizer slots
    partitioned 1/N over the mesh's dedicated ``fsdp`` axis.

    Same flat ``(N, chunk)`` packing as ZeRO-1, promoted from optimizer
    slots to the parameters themselves:

    - **storage** — every planned parameter lives packed ``(N, chunk)``
      sharded ``P(fsdp)`` (``pack_params``); each device permanently
      holds 1/N of it. The fsdp axis ALSO carries batch rows
      (``mesh.batch_axes`` includes it), so the data-parallel story is
      unchanged — only parameter residency shrinks, which is how a
      model ~N× one device's memory trains on an N-device mesh.
    - **gather-on-use** — ``full_params`` rebuilds each full parameter
      inside the jitted step with ONE all-gather over fsdp per
      parameter (a ``with_sharding_constraint`` to replicated, then the
      unpad/reshape). Per layer, deliberately: the largest gathered
      buffer is one layer's parameter, never the whole model — the
      full-gather-materialization smell graftlint PT604 rejects.
    - **backward** — the gather's transpose makes XLA reduce the
      per-device partial gradients back INTO the packed layout
      (reduce-scatter, or all-reduce + local slice — whichever the
      partitioner picks is pinned in ``analysis/comm_budget.toml``).
    - **update** — the ZeRO shard-wise update on the local rows, with
      NO trailing all-gather: the updated parameter stays sharded and
      the next step's forward re-gathers it. Slots pack identically
      (``convert_state`` inherited), so ``--use_zero1`` composes as a
      no-op — FSDP already holds slots at 1/N.

    Packing padding stays EXACTLY zero across steps: the unpack slice's
    transpose writes zero cotangents into the pad region and every
    dense optimizer maps (0 param, 0 grad, 0 slots) to 0, so the
    gather-on-save/reshard-on-load checkpoint round trip (full shapes
    on disk, the zero1/pipeline format precedent) is lossless.

    Exactness: the gathered forward is bit-identical to the unsharded
    one (the gather reconstructs exact bits) and the shard-wise update
    is the proven zero1 elementwise math; only the gradient REDUCTION
    order may differ from plain DP's all-reduce, so parity vs the
    unsharded step is asserted at 1e-7, not bitwise
    (``tests/test_fsdp.py``) — while exact resume (same program twice)
    stays bitwise (``tests/test_exact_resume_matrix.py``).
    """

    def __init__(self, optimizer: Optimizer, mesh, params: Dict[str, Any],
                 meta: Optional[Dict[str, ParamSpec]] = None,
                 rules: Optional[Dict[str, P]] = None,
                 overlap=True, graph=None):
        if mesh_lib.FSDP_AXIS not in mesh.axis_names or \
                dict(mesh.shape)[mesh_lib.FSDP_AXIS] <= 1:
            raise ValueError(
                f"FSDP needs a {mesh_lib.FSDP_AXIS!r} mesh axis of size "
                "> 1; build one with create_mesh(n_fsdp=N) (callers "
                "stand down to the replicated step)")
        super().__init__(optimizer, mesh, params, meta, rules=rules,
                         fsdp=True)
        # the double-buffer prefetch order: planned names sorted by
        # first consumer in the network's topo order (SpecLayout is the
        # ONE derivation point; falls back to the given — alphabetical
        # init — order without a graph)
        self.schedule: List[str] = self.layout.prefetch_schedule(
            list(self.plan), graph)
        if overlap and len(self.plan) < 2:
            from paddle_tpu.utils.log import logger
            logger.warning(
                "FSDP overlap: only %d planned parameter(s) — nothing "
                "to double-buffer; standing down to the sync gather "
                "spelling", len(self.plan))
            overlap = False
        # True/False = auto (chain on TPU only); "force" = always chain
        self.overlap_mode = overlap

    def _overlap_active(self) -> bool:
        """Does THIS trace stage the double-buffer gather chain? Forced
        mode wins (tests / bench A/B); otherwise the chain is TPU-only —
        the CPU audit compiles must stage the sync spelling the pinned
        comm/mem budgets describe (the byte-identity is separately
        regression-tested by forcing the chain, ``tests/test_analysis``)."""
        if _OVERLAP_FORCED == "off":
            return False
        if _OVERLAP_FORCED == "force" or self.overlap_mode == "force":
            return True
        if not self.overlap_mode:
            return False
        return jax.default_backend() == "tpu"

    def gather_peak_bytes(self) -> int:
        """Per-device transient gathered-buffer peak: the largest single
        gathered parameter under the sync spelling, the largest ADJACENT
        PAIR in schedule order under double-buffering (two layers'
        buffers live while gather k+1 flies behind layer k's compute) —
        the number ``utils/profiler.py:memory_stats`` reports so
        ``--show_step_breakdown`` agrees with the compiled truth."""
        sizes = []
        for name in self.schedule:
            _, _, chunk = self.plan[name]
            itemsize = self.dtypes.get(name, np.dtype(np.float32)).itemsize
            sizes.append(self.n * chunk * itemsize)
        if not sizes:
            return 0
        if not self._overlap_active() or len(sizes) == 1:
            return max(sizes)
        return max(a + b for a, b in zip(sizes, sizes[1:]))

    # -------------------------------------------------- parameter layout
    def _is_packed(self, x, name: str) -> bool:
        _, _, chunk = self.plan[name]
        return (getattr(x, "ndim", 0) == 2
                and tuple(x.shape) == (self.n, chunk))

    def pack_params(self, params):
        """Full-shape params -> the storage layout: planned leaves
        packed ``(N, chunk)`` sharded ``P(fsdp)``. Eager (enable/load
        time); idempotent on already-packed-and-placed leaves. A leaf
        whose FULL shape happens to equal ``(N, chunk)`` (an N-row fc
        weight) is a shape coincidence, not a packed leaf — packing is
        the identity reshape for it, but it must still be RESHARDED or
        it sits replicated at full per-device bytes (review-round
        finding; regression-tested)."""
        sharding = self._slot_sharding()
        out = dict(params)
        for name in self.plan:
            leaf = out.get(name)
            if leaf is None:
                continue
            if self._is_packed(leaf, name) and \
                    getattr(leaf, "sharding", None) == sharding:
                continue
            if not self._is_packed(leaf, name):
                leaf = self._pack_host(jax.device_get(leaf), name)
            out[name] = jax.device_put(leaf, sharding)
        return out

    def unpack_params(self, params):
        """Storage -> full shapes (jnp ops: works eagerly for the
        checkpoint/eval view and under a trace). The eager spelling
        performs the gather as a device op — ``_params_for_save`` passes
        this lazily so saves not due pay nothing."""
        out = dict(params)
        for name in self.plan:
            leaf = out.get(name)
            if leaf is not None and self._is_packed(leaf, name):
                out[name] = self._unpack(leaf, name)
        return out

    def full_params(self, params):
        """The gather-on-use view inside the jitted step: per planned
        parameter, pin the packed leaf replicated (ONE all-gather over
        the fsdp axis) and unpad/reshape to the full shape. The rest of
        the step — forward, backward, metrics — consumes the result
        exactly as it consumes replicated parameters.

        Overlap spelling (``_overlap_active``): the gathers are chained
        with ``optimization_barrier`` in prefetch-schedule order — the
        packed input of gather k+1 is fenced on gather k's OUTPUT, so
        the scheduler can fly at most one gather ahead of its consumer
        (gather k+1 behind layer k's compute: classic double-buffering,
        peak = two gathered layers, never the whole model) while each
        layer's compute is free to overlap the next gather. The barrier
        is the identity on values, adds NO collectives (graftlint pass 4
        budgets byte-identically; regression-tested), and its transpose
        is the same chain reversed — the backward's grad reduce-scatters
        are fenced pairwise too, overlapping the PREVIOUS layer's
        backward compute symmetrically."""
        rep = NamedSharding(self.mesh, P())
        out = dict(params)
        if not self._overlap_active():
            for name in self.plan:
                leaf = out.get(name)
                if leaf is not None:
                    out[name] = self._unpack(
                        jax.lax.with_sharding_constraint(leaf, rep), name)
            return out
        names = [n for n in self.schedule if out.get(n) is not None]
        gathered: Dict[str, Any] = {}
        prev = None
        for name in names:
            leaf = out[name]
            if prev is not None:
                leaf, gathered[prev] = _prefetch_fence(
                    leaf, gathered[prev])
            gathered[name] = jax.lax.with_sharding_constraint(leaf, rep)
            prev = name
        for name in names:
            out[name] = self._unpack(gathered[name], name)
        return out

    def pack_params_host(self, params):
        """Host-side packing of a restored full-shape param dict (numpy
        in, numpy out) so ``SGD.load_state``'s place() sees arrays
        matching the live packed leaves."""
        out = dict(params)
        for name in self.plan:
            if name in out:
                arr = np.asarray(out[name])
                _, _, chunk = self.plan[name]
                if arr.ndim == 2 and arr.shape == (self.n, chunk):
                    continue  # already packed (a same-mode resume)
                out[name] = self._pack_host(arr, name)
        return out

    # --------------------------------------------------------------- update
    def update(self, grads, state, params,
               meta: Optional[Dict[str, ParamSpec]] = None,
               batch_size=1, num_passes=0):
        """Shard-wise update on the packed storage: planned parameters
        and their gradients arrive ``(N, chunk)`` (the gather's
        transpose already reduced the cotangent into the packed
        layout), fuse along the chunk dim, update each device's row,
        and RETURN THE SHARDS — no trailing all-gather; the next
        forward's per-layer gather is the only reconstruction."""
        from paddle_tpu.optim.schedules import learning_rate_at
        if "avg" in state:
            raise ValueError(
                "FSDP does not compose with model averaging ('avg' "
                "state is consumed whole at eval/save time); "
                "enable_fsdp stands down before building this updater")
        opt = self.opt
        meta = meta if meta is not None else self.meta

        t = state["t"] + 1
        num_samples = state["num_samples"] + batch_size
        lr_t = learning_rate_at(
            opt.learning_rate_schedule, opt.learning_rate,
            opt.learning_rate_decay_a, opt.learning_rate_decay_b,
            num_samples, args=opt.learning_rate_args, num_passes=num_passes)
        if opt.sum_gradients:
            bsz = jnp.asarray(batch_size, jnp.float32)
            grads = {n: g * bsz for n, g in grads.items()}

        new_params = dict(params)
        new_slots = {n: s for n, s in state["slots"].items()
                     if n not in grads}
        z_names = sorted(n for n in grads
                         if n in self.plan and n in state["slots"])

        # fallback set: sparse lazy tables, ruled (model/pipe) params,
        # grads for slot-less params — the replicated per-param body,
        # identical to Optimizer.update (and to Zero1Updater's)
        for name, g in grads.items():
            if name in z_names:
                continue
            if name not in state["slots"]:
                new_params[name] = params[name]
                continue
            spec = meta.get(name) if meta else None
            p_new, s_new = opt._update_param(
                g, params[name], state["slots"][name], spec, lr_t, t)
            new_params[name] = p_new
            new_slots[name] = s_new

        if z_names:
            # one fused (N, sum_chunks) buffer per role, exactly the
            # zero1 bucketing — except the operands are ALREADY packed
            # and sharded, so the concatenate runs shard-wise. The pins
            # keep propagation honest (graftlint PT503: a pack feeding
            # a sharded shard_map in_spec must carry a constraint).
            offs, off = {}, 0
            for n in z_names:
                chunk = self.plan[n][2]
                offs[n] = (off, off + chunk)
                off += chunk
            shd = self._slot_sharding()
            p_fused = jax.lax.with_sharding_constraint(jnp.concatenate(
                [params[n] for n in z_names], axis=1), shd)
            g_fused = jax.lax.with_sharding_constraint(jnp.concatenate(
                [grads[n] for n in z_names], axis=1), shd)
            s_sh = {n: state["slots"][n] for n in z_names}
            specs = {n: (meta.get(n) if meta else None) for n in z_names}

            def shard_update(p_loc, g_loc, s_sh, lr_t, t):
                # this device's (1, sum_chunks) row + its slot rows:
                # the elementwise update math is the replicated path's,
                # applied to 1/N of every parameter — and the result
                # STAYS here (no gather; the next forward re-gathers)
                out_p, out_s = [], {}
                for n in z_names:
                    lo, hi = offs[n]
                    p1, s1 = opt._update_param(
                        g_loc[:, lo:hi], p_loc[:, lo:hi], s_sh[n],
                        specs[n], lr_t, t)
                    out_p.append(p1)
                    out_s[n] = s1
                return jnp.concatenate(out_p, axis=1), out_s

            fused_new, s_new = mesh_lib.shard_map_compat(
                shard_update, self.mesh,
                in_specs=(P(self.axes), P(self.axes), P(self.axes),
                          P(), P()),
                out_specs=(P(self.axes), P(self.axes)))(p_fused, g_fused,
                                                        s_sh, lr_t, t)
            for n in z_names:
                lo, hi = offs[n]
                new_params[n] = jax.lax.with_sharding_constraint(
                    fused_new[:, lo:hi], shd)
                new_slots[n] = s_new[n]

        return new_params, {"slots": new_slots, "t": t,
                            "num_samples": num_samples}

    # ------------------------------------------------- delegated protocol
    def prune_params(self, params, state):
        """Pruning masks live at full shapes: gather, prune, re-pack."""
        full = self.unpack_params(params)
        pruned = self.opt.prune_params(full, self.gather_opt_state(state))
        return self.pack_params(pruned)
