"""Optimizers with reference v1 semantics, as pure pytree transforms.

Update formulas match the fused kernels in
``paddle/math/TrainingAlgorithmOp.cu`` (adadelta ``:43``, adagrad ``:66``,
rmsprop ``:86``, decayed-adagrad ``:117``, adam ``:146``, adamax ``:166``)
and the optimizer classes in ``paddle/parameter/FirstOrderOptimizer.h``.
L2 regularization enters the update as ``decayRate`` exactly as there
(``grad + value*decayRate``); L1 is a post-update shrink
(``OptimizerWithRegularizer``). Per-parameter lr multipliers and static
params mirror ``ParameterConfig.learning_rate`` / ``is_static``.

The whole update is one jitted pytree map — the TPU replacement for the
reference's per-block pserver/threaded updaters.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import ParamSpec


@dataclasses.dataclass
class Optimizer:
    """Base: shared hyper-parameters (``OptimizationConfig`` in
    proto/TrainerConfig.proto)."""

    learning_rate: float = 1e-3
    learning_rate_schedule: str = "constant"
    learning_rate_decay_a: float = 0.0
    learning_rate_decay_b: float = 0.0
    learning_rate_args: str = ""
    l1_rate: float = 0.0
    l2_rate: float = 0.0
    gradient_clipping_threshold: float = 0.0
    # model averaging (``AverageOptimizer``): fraction of updates kept in
    # the average (TrainerConfig.proto:74); >= 1 acts as an absolute window
    average_window: float = 0.0
    max_average_window: float = float("inf")
    # Reference v1 gradient semantics (compat configs): parameter grads
    # are the batch SUM (sgdUpdateCpu applies learning_rate to the
    # accumulated gradient; ParameterUpdateFunctions.cpp:25-36, no batch
    # normalization). The engine differentiates the batch-MEAN cost, so
    # with this flag the update multiplies grads by the ACTUAL batch
    # size before clipping/decay — keeping learning_rate, clipping
    # thresholds, L1/L2 rates, and schedules at their reference values.
    sum_gradients: bool = False

    # -- per-subclass ---------------------------------------------------
    def slot_names(self):
        return []

    def _apply_one(self, p, g, slots, lr, decay, t):
        raise NotImplementedError

    # -- public ---------------------------------------------------------
    def create_local_updater(self):
        """The v2-on-SWIG idiom (``optimizer.py:45-56`` →
        ``api.ParameterUpdater::createLocalUpdater``): an updater driving
        this optimizer through the startBatch/update/finishBatch
        protocol."""
        from paddle_tpu.compat.swig_api import ParameterUpdater
        return ParameterUpdater(self)

    def enable_types(self):
        """Parameter buffer types this optimizer maintains
        (``ParameterOptimizer::getParameterTypes``: always VALUE and
        GRADIENT, plus one slot type per optimizer state buffer); the api
        surface passes this to createFromConfigProto."""
        return [0, 1] + [i + 2 for i, _ in enumerate(self.slot_names())]

    def _is_sparse(self, spec) -> bool:
        # the lazy touched-rows path implements the PLAIN momentum
        # recurrence; nesterov's lookahead has no closed-form row
        # catch-up, so those parameters take the dense path (correct,
        # just not lazy) to keep the documented dense==sparse property
        return (spec is not None and getattr(spec, "sparse_grad", False)
                and hasattr(self, "_apply_sparse")
                and not getattr(self, "nesterov", False))

    def init(self, params: Dict[str, jnp.ndarray],
             meta: Optional[Dict[str, ParamSpec]] = None) -> Dict[str, Any]:
        slots = {}
        for name, p in params.items():
            spec = meta.get(name) if meta else None
            if spec is not None and spec.is_static:
                continue
            d = {s: jnp.zeros_like(p) for s in self.slot_names()}
            if spec is not None and spec.sparsity_ratio:
                # StaticPruningHook (ParameterUpdaterHook.cpp:39): mask the
                # smallest-|w| fraction at init; update() keeps them zero
                thresh = jnp.quantile(jnp.abs(p), spec.sparsity_ratio)
                d["prune_mask"] = (jnp.abs(p) >= thresh).astype(p.dtype)
            if self._is_sparse(spec):
                # per-row last-processed step for lazy (touched-rows-only)
                # updates — the SparseRowMatrix/catchUpWith bookkeeping
                # (SparseRowMatrix.h:204, OptimizerWithRegularizer.h)
                d["t_rows"] = jnp.zeros((p.shape[0],), jnp.int32)
            slots[name] = d
        state = {"slots": slots, "t": jnp.zeros((), jnp.int32),
                 "num_samples": jnp.zeros((), jnp.float32)}
        if self.average_window > 0:
            state["avg"] = {n: jnp.zeros_like(p) for n, p in params.items()
                            if n in slots}
        return state

    def _update_param(self, g, p, slots, spec, lr_t, t):
        """One parameter's update: clipping, l1/l2 resolution, the dense or
        sparse apply, and the prune mask. Shape-agnostic and elementwise
        (except the sparse lazy path), so the ZeRO-1 updater
        (``optim/zero1.py``) runs the same code on each device's 1/N flat
        shard — one source of truth for update semantics. Clipping happens
        HERE, on whatever gradient the caller accumulated: under microbatch
        gradient accumulation that is the accumulation-averaged gradient,
        never a per-microbatch one (the reference clips the full batch's
        accumulated gradient, ``FirstOrderOptimizer.h``)."""
        lr_mult = spec.learning_rate if spec else 1.0
        l2 = spec.l2_rate if spec and spec.l2_rate is not None else self.l2_rate
        l1 = spec.l1_rate if spec and spec.l1_rate is not None else self.l1_rate
        if self.gradient_clipping_threshold > 0:
            # reference clips per-parameter by value threshold
            # (FirstOrderOptimizer.h, clipping in SgdOptimizer variants)
            th = self.gradient_clipping_threshold
            g = jnp.clip(g, -th, th)
        mask = slots.get("prune_mask")
        if self._is_sparse(spec):
            # touched-rows-only update with momentum/decay catch-up;
            # l1/l2 handled inside (deferred per-row)
            p_new, slots_new = self._apply_sparse(
                p, g, slots, lr_t * lr_mult, l1, l2, t)
        else:
            # the dense elementwise chain routes through the fused-kernel
            # plane (kernels/opt_update.py): Pallas-on-TPU for the
            # Momentum/Adam chains, _apply_one itself everywhere else —
            # so the replicated, ZeRO-1 shard-wise and packed FSDP
            # updates all share the one fused entry
            from paddle_tpu.kernels import opt_update as _fused
            p_new, slots_new = _fused.apply_one(
                self, p, g, slots, lr_t * lr_mult, l2, t)
            if l1 > 0:
                shrink = l1 * lr_t * lr_mult
                p_new = jnp.sign(p_new) * jnp.maximum(
                    jnp.abs(p_new) - shrink, 0.0)
        if mask is not None:
            p_new = p_new * mask          # pruned weights stay zero
            slots_new["prune_mask"] = mask
        return p_new, slots_new

    def update(self, grads, state, params,
               meta: Optional[Dict[str, ParamSpec]] = None,
               batch_size=1, num_passes=0):
        """(grads, state, params) -> (new_params, new_state). meta carries
        per-param lr multipliers / static flags / l1-l2 overrides;
        ``num_passes`` (current pass id) drives the pass_manual schedule."""
        from paddle_tpu.optim.schedules import learning_rate_at

        t = state["t"] + 1
        num_samples = state["num_samples"] + batch_size
        lr_t = learning_rate_at(
            self.learning_rate_schedule, self.learning_rate,
            self.learning_rate_decay_a, self.learning_rate_decay_b,
            num_samples, args=self.learning_rate_args,
            num_passes=num_passes)

        new_params = dict(params)
        # parameters whose gradient is absent this call keep their slots
        # untouched (an API caller updating a subset must not erase
        # momentum history / prune masks / t_rows for the rest)
        new_slots = {n: s for n, s in state["slots"].items()
                     if n not in grads}
        if self.sum_gradients:
            bsz = jnp.asarray(batch_size, jnp.float32)
            grads = {n: g * bsz for n, g in grads.items()}
        for name, g in grads.items():
            if name not in state["slots"]:
                new_params[name] = params[name]
                continue
            spec = meta.get(name) if meta else None
            p_new, slots_new = self._update_param(
                g, params[name], state["slots"][name], spec, lr_t, t)
            new_params[name] = p_new
            new_slots[name] = slots_new

        new_state = {"slots": new_slots, "t": t, "num_samples": num_samples}
        if "avg" in state:
            new_state["avg"] = self._update_avg(state["avg"], t, new_params,
                                                new_slots)
        return new_params, new_state

    def _update_avg(self, avg, t, new_params, new_slots):
        """AverageOptimizer: the window is a FRACTION of all updates so
        far — about average_window * numUpdates parameters are averaged
        (TrainerConfig.proto:70-74), capped by max_average_window
        (AverageOptimizer.h:83-88). Running average with the growing
        effective window W_t = clip(average_window * t, 1,
        max_average_window); values >= 1 behave as an absolute window.
        Shared by the replicated update and the ZeRO-1 updater (which
        keeps ``avg`` replicated) — one source of truth for the window
        semantics."""
        tf32 = t.astype(jnp.float32)
        w = jnp.clip(jnp.float32(self.average_window) * tf32,
                     1.0, jnp.float32(self.max_average_window))
        w = jnp.minimum(tf32, w)
        return {n: avg[n] + (new_params[n] - avg[n]) / w
                for n in new_slots}

    def prune_params(self, params, state):
        """Zero the masked weights immediately — the reference's
        StaticPruningHook::init dotMul's the mask into the value before
        any step runs, so forwards/checkpoints before the first update
        already see pruned weights."""
        out = dict(params)
        for name, slots in state["slots"].items():
            if "prune_mask" in slots and name in out:
                out[name] = out[name] * slots["prune_mask"]
        return out

    def catch_up(self, params, state,
                 meta: Optional[Dict[str, ParamSpec]] = None,
                 num_passes: int = 0):
        """Apply deferred sparse-row updates to ALL rows (the reference's
        ``catchUpWith``, ``OptimizerWithRegularizer.h``): run at pass end
        and before checkpoints so lazily-updated tables are current. Uses
        the current learning rate for the missed steps, as the reference
        does; ``num_passes`` keeps pass-based schedules on the right rate."""
        if not any("t_rows" in s for s in state["slots"].values()):
            return params, state
        from paddle_tpu.optim.schedules import learning_rate_at
        lr_t = learning_rate_at(
            self.learning_rate_schedule, self.learning_rate,
            self.learning_rate_decay_a, self.learning_rate_decay_b,
            state["num_samples"], args=self.learning_rate_args,
            num_passes=num_passes)
        new_params = dict(params)
        new_slots = dict(state["slots"])
        for name, slots in state["slots"].items():
            if "t_rows" not in slots:
                continue
            spec = meta.get(name) if meta else None
            lr_mult = spec.learning_rate if spec else 1.0
            l2 = (spec.l2_rate if spec and spec.l2_rate is not None
                  else self.l2_rate)
            l1 = (spec.l1_rate if spec and spec.l1_rate is not None
                  else self.l1_rate)
            p2, s2 = self._sparse_catch_up_one(
                params[name], slots, lr_t * lr_mult, l1, l2, state["t"])
            if "prune_mask" in slots:
                p2 = p2 * slots["prune_mask"]
                s2["prune_mask"] = slots["prune_mask"]
            new_params[name] = p2
            new_slots[name] = s2
        return new_params, {**state, "slots": new_slots}

    def averaged_params(self, state, params):
        """``AverageOptimizer::apply`` (AverageOptimizer.h:23): swap in the
        windowed average of each learnable parameter for evaluation; the raw
        trained values stay in ``params`` (≡ ``restore``)."""
        if "avg" not in state:
            return params
        out = dict(params)
        out.update(state["avg"])
        return out


@dataclasses.dataclass
class Momentum(Optimizer):
    """Classic v1 SGD+momentum (``sgdUpdate``):
    mom = momentum*mom - lr*(grad + decayRate*value); value += mom.
    ``nesterov`` mirrors ``SparseMomentumParameterOptimizer``'s
    lookahead formulation (FirstOrderOptimizer.h:64-122) collapsed to its
    dense equivalent."""

    momentum: float = 0.0
    nesterov: bool = False

    def slot_names(self):
        return ["mom"]

    def _apply_one(self, p, g, slots, lr, decay, t):
        mom = self.momentum * slots["mom"] - lr * (g + decay * p)
        if self.nesterov:
            return p + self.momentum * mom - lr * (g + decay * p), \
                {"mom": mom}
        return p + mom, {"mom": mom}

    # ---------------------------------------------------- sparse (lazy) path
    # Touched-rows-only updates for sparse_grad tables, with closed-form
    # catch-up. For a row with zero grad the dense recurrence is
    # mom *= mu; p += mom — over k missed steps p += mom*(mu+...+mu^k) and
    # mom *= mu^k, applied lazily when the row is next touched (or at
    # catch_up). Exactly equal to the dense updater when l1=l2=0 (the
    # test_CompareSparse property); with regularization the decay is
    # deferred per-row as (1-lr*l2)^k / k-scaled l1 shrink, the reference's
    # OptimizerWithRegularizerSparse approximation.

    def _geo_sum(self, k):
        """mu + mu^2 + ... + mu^k, elementwise over int k."""
        mu = self.momentum
        kf = k.astype(jnp.float32)
        if mu == 1.0:
            return kf
        if mu == 0.0:
            return jnp.zeros_like(kf)
        return mu * (1.0 - jnp.power(mu, kf)) / (1.0 - mu)

    def _catch_up_rows(self, p, mom, lr, l1, l2, k):
        kf = k.astype(p.dtype).reshape(k.shape + (1,) * (p.ndim - 1))
        if l2 > 0:
            p = p * jnp.power(1.0 - lr * l2, kf)
        if l1 > 0:
            shrink = lr * l1 * kf
            p = jnp.sign(p) * jnp.maximum(jnp.abs(p) - shrink, 0.0)
        geo = self._geo_sum(k).reshape(kf.shape)
        p = p + mom * geo
        mom = mom * jnp.power(self.momentum, kf) if self.momentum > 0 \
            else jnp.where(kf > 0, 0.0, mom)
        return p, mom

    def _apply_sparse(self, p, g, slots, lr, l1, l2, t):
        t_rows = slots["t_rows"]
        touched = jnp.any(g != 0, axis=tuple(range(1, g.ndim)))
        k = (t - 1) - t_rows  # steps missed before this one
        cp, cmom = self._catch_up_rows(p, slots["mom"], lr, l1, l2, k)
        mom_new = self.momentum * cmom - lr * (g + l2 * cp)
        p_new = cp + mom_new
        if l1 > 0:
            # the live step's shrink (catch-up covered only missed steps)
            p_new = jnp.sign(p_new) * jnp.maximum(
                jnp.abs(p_new) - lr * l1, 0.0)
        tb = touched.reshape(touched.shape + (1,) * (p.ndim - 1))
        return (jnp.where(tb, p_new, p),
                {"mom": jnp.where(tb, mom_new, slots["mom"]),
                 "t_rows": jnp.where(touched, t, t_rows)})

    def _sparse_catch_up_one(self, p, slots, lr, l1, l2, t):
        k = t - slots["t_rows"]
        p2, mom2 = self._catch_up_rows(p, slots["mom"], lr, l1, l2, k)
        return p2, {"mom": mom2,
                    "t_rows": jnp.full_like(slots["t_rows"], t)}


@dataclasses.dataclass
class AdaGrad(Optimizer):
    """``adagradApply`` (TrainingAlgorithmOp.cu:66)."""

    momentum: float = 0.0
    epsilon: float = 1e-6

    def slot_names(self):
        return ["mom", "accum"]

    def _apply_one(self, p, g, slots, lr, decay, t):
        accum = slots["accum"] + jnp.square(g)
        scale = jax.lax.rsqrt(accum + self.epsilon)
        mom = self.momentum * slots["mom"] - lr * scale * (g + decay * p)
        return p + mom, {"mom": mom, "accum": accum}


@dataclasses.dataclass
class AdaDelta(Optimizer):
    """``adadeltaApply`` (TrainingAlgorithmOp.cu:43)."""

    rou: float = 0.95
    epsilon: float = 1e-6
    momentum: float = 0.0

    def slot_names(self):
        return ["mom", "accum", "accum_update"]

    def _apply_one(self, p, g, slots, lr, decay, t):
        accum = self.rou * slots["accum"] + (1 - self.rou) * jnp.square(g)
        lr_vec = jnp.sqrt((slots["accum_update"] + self.epsilon)
                          / (accum + self.epsilon))
        accum_update = (self.rou * slots["accum_update"]
                        + (1 - self.rou) * jnp.square(g * lr_vec))
        mom = self.momentum * slots["mom"] - lr * lr_vec * (g + decay * p)
        return p + mom, {"mom": mom, "accum": accum,
                         "accum_update": accum_update}


@dataclasses.dataclass
class RMSProp(Optimizer):
    """``rmspropApply`` (TrainingAlgorithmOp.cu:86): centered RMSProp with
    mean-subtracted second moment."""

    rou: float = 0.95
    epsilon: float = 1e-6
    momentum: float = 0.0

    def slot_names(self):
        return ["mom", "g", "f"]

    def _apply_one(self, p, g, slots, lr, decay, t):
        acc_g = self.rou * slots["g"] + (1 - self.rou) * jnp.square(g)
        acc_f = self.rou * slots["f"] + (1 - self.rou) * g
        scale = jax.lax.rsqrt(acc_g - jnp.square(acc_f) + self.epsilon)
        mom = self.momentum * slots["mom"] - lr * scale * (g + decay * p)
        return p + mom, {"mom": mom, "g": acc_g, "f": acc_f}


@dataclasses.dataclass
class DecayedAdaGrad(Optimizer):
    """``decayedAdagradApply`` (TrainingAlgorithmOp.cu:117)."""

    rou: float = 0.95
    epsilon: float = 1e-6
    momentum: float = 0.0

    def slot_names(self):
        return ["mom", "accum"]

    def _apply_one(self, p, g, slots, lr, decay, t):
        accum = self.rou * slots["accum"] + (1 - self.rou) * jnp.square(g)
        scale = jax.lax.rsqrt(accum + self.epsilon)
        mom = self.momentum * slots["mom"] - lr * scale * (g + decay * p)
        return p + mom, {"mom": mom, "accum": accum}


@dataclasses.dataclass
class Adam(Optimizer):
    """``adamApply`` (TrainingAlgorithmOp.cu:146). decay enters via grad as
    in ``AdamOptimizer::update`` (FirstOrderOptimizer.h)."""

    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def slot_names(self):
        return ["mom", "v"]

    def _apply_one(self, p, g, slots, lr, decay, t):
        g = g + decay * p
        mom = self.beta1 * slots["mom"] + (1 - self.beta1) * g
        v = self.beta2 * slots["v"] + (1 - self.beta2) * jnp.square(g)
        tf = t.astype(jnp.float32)
        alpha = lr * jnp.sqrt(1 - jnp.power(self.beta2, tf)) \
            / (1 - jnp.power(self.beta1, tf))
        return p - alpha * mom / (jnp.sqrt(v) + self.epsilon), \
            {"mom": mom, "v": v}


@dataclasses.dataclass
class Adamax(Optimizer):
    """``adamaxApply`` (TrainingAlgorithmOp.cu:166)."""

    beta1: float = 0.9
    beta2: float = 0.999

    def slot_names(self):
        return ["mom", "u"]

    def _apply_one(self, p, g, slots, lr, decay, t):
        g = g + decay * p
        mom = self.beta1 * slots["mom"] + (1 - self.beta1) * g
        u = jnp.maximum(self.beta2 * slots["u"], jnp.abs(g))
        tf = t.astype(jnp.float32)
        step = lr / (1 - jnp.power(self.beta1, tf))
        return p - step * mom / jnp.maximum(u, 1e-12), {"mom": mom, "u": u}


_BY_NAME = {
    "momentum": Momentum, "sgd": Momentum, "adagrad": AdaGrad,
    "adadelta": AdaDelta, "rmsprop": RMSProp,
    "decayed_adagrad": DecayedAdaGrad, "adam": Adam, "adamax": Adamax,
}


def create_optimizer(name: str, **kwargs) -> Optimizer:
    """Factory mirroring ``ParameterOptimizer::create``
    (``paddle/parameter/ParameterOptimizer.cpp``)."""
    if name not in _BY_NAME:
        raise KeyError(f"unknown optimizer {name!r}; have {sorted(_BY_NAME)}")
    return _BY_NAME[name](**kwargs)
