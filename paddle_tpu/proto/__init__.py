"""The 8-schema protobuf contract (reference `proto/` directory).

These messages are the canonical model/job description: the config
compiler (`paddle_tpu.compat.config_parser`) emits them, the lowering pass
(`paddle_tpu.compat.lowering`) turns ``ModelConfig`` into the executable
graph, and serialized configs interoperate with the reference's wire
format (same fields and tags). Regenerate with ``gen.sh`` after editing
``defs/*.proto``.
"""

from .DataConfig_pb2 import DataConfig, FileGroupConf  # noqa: F401
from .DataFormat_pb2 import (DataHeader, DataSample, SlotDef,  # noqa: F401
                             SubseqSlot, VectorSlot)
from .ModelConfig_pb2 import (EvaluatorConfig, LayerConfig,  # noqa: F401
                              LayerInputConfig, ModelConfig,
                              ProjectionConfig, OperatorConfig,
                              SubModelConfig)
from .OptimizerConfig_pb2 import OptimizerConfig  # noqa: F401
from .ParameterConfig_pb2 import (ParameterConfig,  # noqa: F401
                                  ParameterUpdaterHookConfig)
from .ParameterServerConfig_pb2 import (ParameterClientConfig,  # noqa: F401
                                        ParameterServerConfig)
from .ParameterService_pb2 import (SendParameterRequest,  # noqa: F401
                                   SendParameterResponse)
from .TrainerConfig_pb2 import (OptimizationConfig,  # noqa: F401
                                TrainerConfig)
