#!/bin/sh
# Regenerate the protobuf Python modules from defs/. Run from this dir.
set -e
cd "$(dirname "$0")"
protoc -I defs --python_out=. defs/*.proto
# gencode imports siblings absolutely ("import X_pb2"); rewrite to relative
# imports so the package works without sys.path games.
python - <<'EOF'
import pathlib, re
for p in pathlib.Path('.').glob('*_pb2.py'):
    src = p.read_text()
    src = re.sub(r'^import (\w+_pb2) as', r'from . import \1 as', src,
                 flags=re.M)
    p.write_text(src)
EOF
