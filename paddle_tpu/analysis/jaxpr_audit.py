"""Pass 2 — trace-time jaxpr/lowering audits.

Static graphs are what make whole-program analysis tractable (the
TensorFlow-paper argument; PAPERS.md), and jitted JAX gives us exactly
that: every hot program in this repo is one traced, inspectable jaxpr.
This pass traces the real programs — the driver entry
(``__graft_entry__.entry()``), a representative bf16 train step, and
the serving warm-path executables — and asserts three invariants the
AST pass can only approximate:

- **PT201 no embedded constants**: a closure-captured device array
  becomes an XLA constant baked into the program (the measured
  ~4x/step deopt, ``core/generation.py:_make_step``). The audit walks
  the traced jaxpr (recursing through pjit/scan/while sub-jaxprs) and
  fails on any constant above ``CONST_LIMIT_BYTES`` — params must be
  traced arguments.
- **PT202 full donation**: every donated input buffer that *can* alias
  an output (matching shape+dtype — XLA's own aliasing precondition)
  must actually be recorded as aliased in the lowered program
  (``tf.aliasing_output``). The train step must donate params and
  optimizer state fully; programs with nothing aliasable pass
  vacuously but still must *declare* their donation.
- **PT203 masks stay f32**: mask leaves of the traced inputs must
  never be converted below f32 inside the program (masks are count
  data; bf16 saturates at 256 — trainer/trainer.py:_cast_compute).
  Taint flows through shape-only ops (reshape/broadcast/slice/...).

Heavy imports (jax, model builders) stay inside functions: Pass 1/3
must not pay them.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from paddle_tpu.analysis.findings import Finding

# anything bigger than this embedded in a program is a captured tensor,
# not a legitimate trace-time constant (iota tables, eos rows and
# similar scaffolding stay well under it)
CONST_LIMIT_BYTES = 64 * 1024

_SHAPE_ONLY_OPS = {
    "reshape", "broadcast_in_dim", "squeeze", "expand_dims",
    "transpose", "slice", "dynamic_slice", "copy", "rev",
}
_LOW_DTYPES = ("bfloat16", "float16")


# ---------------------------------------------------------------- helpers
def _walk_consts(closed) -> List[Tuple[Any, str]]:
    """(const, where) for every const of a ClosedJaxpr, recursing into
    sub-jaxprs carried in eqn params (pjit/scan/while/cond bodies)."""
    out: List[Tuple[Any, str]] = []
    seen = set()

    def rec(cj, where):
        if id(cj) in seen:
            return
        seen.add(id(cj))
        consts = getattr(cj, "consts", None) or []
        for c in consts:
            out.append((c, where))
        jaxpr = getattr(cj, "jaxpr", cj)
        for eqn in getattr(jaxpr, "eqns", []):
            for k, v in eqn.params.items():
                for sub in (v if isinstance(v, (list, tuple)) else [v]):
                    if hasattr(sub, "jaxpr") or hasattr(sub, "eqns"):
                        rec(sub, f"{where}/{eqn.primitive.name}")

    rec(closed, "jaxpr")
    return out


def _const_findings(closed, name: str, anchor: str) -> List[Finding]:
    findings = []
    for const, where in _walk_consts(closed):
        nbytes = getattr(const, "nbytes", 0)
        if nbytes and nbytes > CONST_LIMIT_BYTES:
            findings.append(Finding(
                "PT201", anchor, 1,
                f"{name}: traced program embeds a "
                f"{int(nbytes)}-byte constant "
                f"(shape {getattr(const, 'shape', '?')}, at {where}) — "
                "a closure-captured array became an XLA program "
                "constant; pass it as a traced argument"))
    return findings


def _mask_findings(closed, mask_positions: Sequence[int], name: str,
                   anchor: str) -> List[Finding]:
    """Taint mask invars; flag converts below f32."""
    findings: List[Finding] = []

    def is_var(v) -> bool:
        # jaxpr operands are Vars or (unhashable) Literals
        return not hasattr(v, "val")

    def rec(jaxpr, tainted):
        for eqn in jaxpr.eqns:
            sub_jaxprs = []
            for v in eqn.params.values():
                for sub in (v if isinstance(v, (list, tuple)) else [v]):
                    if hasattr(sub, "jaxpr"):
                        sub_jaxprs.append(sub.jaxpr)
                    elif hasattr(sub, "eqns"):
                        sub_jaxprs.append(sub)
            prim = eqn.primitive.name
            in_taint = [is_var(v) and v in tainted
                        for v in eqn.invars]
            if prim == "convert_element_type" and any(in_taint):
                new_dtype = str(eqn.params.get("new_dtype"))
                if any(d in new_dtype for d in _LOW_DTYPES):
                    findings.append(Finding(
                        "PT203", anchor, 1,
                        f"{name}: a mask input is converted to "
                        f"{new_dtype} inside the traced program; "
                        "masks are f32 count data (bf16 saturates at "
                        "256)"))
                continue
            if sub_jaxprs:
                # map outer invars -> each sub-jaxpr's invars by
                # position tail-aligned (scan/pjit prepend consts)
                for sj in sub_jaxprs:
                    inner_tainted = set()
                    n = min(len(eqn.invars), len(sj.invars))
                    for i in range(1, n + 1):
                        v = eqn.invars[-i]
                        if is_var(v) and v in tainted:
                            inner_tainted.add(sj.invars[-i])
                    if inner_tainted:
                        rec(sj, inner_tainted)
                # a call's outputs may also carry taint; propagating
                # through would need per-output dataflow — the direct
                # convert check above already covers the _cast_compute
                # shape of the bug
            if prim in _SHAPE_ONLY_OPS and any(in_taint):
                for ov in eqn.outvars:
                    tainted.add(ov)

    jaxpr = closed.jaxpr
    tainted = {jaxpr.invars[i] for i in mask_positions
               if i < len(jaxpr.invars)}
    if tainted:
        rec(jaxpr, tainted)
    return findings


def _flatten_with_names(tree) -> List[Tuple[str, Any]]:
    import jax
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(jax.tree_util.keystr(path), leaf) for path, leaf in flat]


def _donation_findings(jitted, args, donate_argnums: Sequence[int],
                       name: str, anchor: str,
                       require_aliasable: bool = False
                       ) -> Tuple[List[Finding], Dict[str, int]]:
    """Lower and verify aliasing: every donated leaf whose (shape,
    dtype) matches an output leaf must be recorded aliased. Returns
    (findings, stats)."""
    import warnings

    import jax
    findings: List[Finding] = []
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # unusable-donation warnings
        lowered = jitted.lower(*args)
        out_shape = jax.eval_shape(jitted, *args)
    txt = lowered.as_text()
    aliased = txt.count("tf.aliasing_output")
    donated_leaves = []
    for i in donate_argnums:
        donated_leaves.extend(
            leaf for _n, leaf in _flatten_with_names(args[i]))
    out_leaves = [leaf for _n, leaf in _flatten_with_names(out_shape)]
    out_pool: Dict[Tuple[Tuple[int, ...], str], int] = {}
    for leaf in out_leaves:
        key = (tuple(leaf.shape), str(leaf.dtype))
        out_pool[key] = out_pool.get(key, 0) + 1
    expected = 0
    for leaf in donated_leaves:
        key = (tuple(getattr(leaf, "shape", ())),
               str(getattr(leaf, "dtype", "")))
        if out_pool.get(key, 0) > 0:
            out_pool[key] -= 1
            expected += 1
    stats = {"donated_leaves": len(donated_leaves),
             "aliasable": expected, "aliased": aliased}
    if aliased < expected:
        findings.append(Finding(
            "PT202", anchor, 1,
            f"{name}: {expected} donated buffers can alias an output "
            f"(matching shape+dtype) but only {aliased} are recorded "
            "aliased in the lowered program — donation is not "
            "reaching XLA"))
    if require_aliasable and expected == 0 and donated_leaves:
        findings.append(Finding(
            "PT202", anchor, 1,
            f"{name}: donation declared but no donated buffer can "
            "alias any output — the donate_argnums are wrong"))
    if not donated_leaves and donate_argnums:
        findings.append(Finding(
            "PT202", anchor, 1,
            f"{name}: donate_argnums {tuple(donate_argnums)} cover no "
            "array leaves"))
    return findings, stats


def _mask_positions(args) -> List[int]:
    return [i for i, (pname, _leaf)
            in enumerate(_flatten_with_names(args))
            if "mask" in pname.lower()]


# ---------------------------------------------------------------- audits
def audit_entry(log=print, root: Optional[str] = None) -> List[Finding]:
    """``__graft_entry__.entry()``: the flagship forward step. Params
    are traced args by contract — zero embedded constants; the
    per-call image buffer is donated (vacuously aliased on a forward
    whose outputs share no buffer shape — the declaration is what the
    audit pins)."""
    import sys

    import jax
    sys.path.insert(0, root or _repo_root())
    try:
        import __graft_entry__ as graft
    finally:
        sys.path.pop(0)
    fn, example = graft.entry()
    anchor = "__graft_entry__.py"
    closed = jax.make_jaxpr(fn)(*example)
    findings = _const_findings(closed, "entry()", anchor)
    jitted = jax.jit(fn, donate_argnums=(1,))
    dfind, stats = _donation_findings(jitted, example, (1,),
                                      "entry()", anchor)
    findings.extend(dfind)
    findings.extend(_mask_findings(closed, _mask_positions(example),
                                   "entry()", anchor))
    if log:
        log(f"  entry(): consts clean, donation {stats}")
    return findings


def _repo_root() -> str:
    from paddle_tpu.analysis._astutil import repo_root
    return repo_root()


def audit_train_step(log=print) -> List[Finding]:
    """A representative bf16 train step (masked LSTM classifier):
    params+opt_state donate fully, masks survive as f32 through the
    lowered program, no embedded constants."""
    import jax
    import numpy as np

    from paddle_tpu.config import dsl
    from paddle_tpu.data import (DataFeeder, integer_value,
                                 integer_value_sequence)
    from paddle_tpu.models import lstm_text_classifier
    from paddle_tpu.optim import Adam
    from paddle_tpu.trainer import SGD

    anchor = "paddle_tpu/trainer/trainer.py"
    dsl.reset()
    cost, _out, _ = lstm_text_classifier(
        vocab_size=32, embed_dim=8, hidden=8, num_layers=1, classes=2)
    trainer = SGD(cost=cost, update_equation=Adam(learning_rate=1e-3),
                  compute_dtype="bfloat16", seed=0)
    rng = np.random.RandomState(0)
    data = [(list(rng.randint(0, 32, size=rng.randint(3, 8))),
             int(rng.randint(0, 2))) for _ in range(4)]
    feeder = DataFeeder({"words": integer_value_sequence(32),
                         "label": integer_value(2)}, pad_multiple=8)
    feed = feeder(data)
    args = (trainer.params, trainer.opt_state, feed,
            jax.random.PRNGKey(0), 0, None)
    closed = jax.make_jaxpr(trainer._train_step)(*args)
    findings = _const_findings(closed, "train_step", anchor)
    dfind, stats = _donation_findings(
        trainer._train_step, args, (0, 1), "train_step", anchor,
        require_aliasable=True)
    findings.extend(dfind)
    mask_pos = _mask_positions(args)
    if not mask_pos:
        findings.append(Finding(
            "PT203", anchor, 1,
            "train_step audit: expected mask leaves in the feed "
            "(audit setup broke)"))
    findings.extend(_mask_findings(closed, mask_pos, "train_step",
                                   anchor))
    if log:
        log(f"  train_step: donation {stats}, "
            f"{len(mask_pos)} mask leaves traced f32-clean")
    return findings


def build_scoring_predictor():
    """The bucketed scoring predictor warm path (masked sequence
    model), built exactly as warmup would compile it (donate=True —
    the TPU/GPU configuration; CPU merely ignores it at run time).
    Shared by the pass-2 donation/constant audit and the pass-4
    collective audit (shard_audit.build_serving_warm): one build, two
    invariants. Returns ``(pred, (params, feed))``."""
    import jax

    from paddle_tpu.config import dsl
    from paddle_tpu.core.network import Network
    from paddle_tpu.data import integer_value, integer_value_sequence
    from paddle_tpu.serving.predictor import (ServingPredictor,
                                              _synth_sample)
    V = 16
    dsl.reset()
    w = dsl.data(name="w", size=V)
    lab = dsl.data(name="label", size=2)
    emb = dsl.embedding(input=w, size=6, name="emb")
    pooled = dsl.pooling(input=emb, pooling_type="avg", name="pool")
    out = dsl.fc(input=pooled, size=2, act="softmax", name="out")
    dsl.classification_cost(input=out, label=lab, name="cost")
    graph = dsl.current_graph()
    params = Network(graph, outputs=["out"]).init_params(
        jax.random.PRNGKey(0))
    pred = ServingPredictor(
        graph, params, ["out"],
        {"w": integer_value_sequence(V), "label": integer_value(2)},
        batch_buckets=[2], length_buckets=[8], donate=True)
    rows = [tuple(_synth_sample(pred.feeding[n], 4)
                  for n in pred.names)] * 2
    feed = pred.feeder(list(rows))
    return pred, (pred.params, feed)


def build_quant_predictor():
    """The int8-quantized twin of :func:`build_scoring_predictor`:
    the SAME model, quantized the way ``--job=merge --quantize=int8``
    writes it, loaded the way the predictor serves it (int8 leaves +
    traced ``::scale`` siblings, dequant fused inside ``_infer``).
    Feeds the pass-4/5 ``serving_quant`` program: its pinned
    per-device bytes ARE the quantization win, and its PT602 law
    measures the params argument against the fp32 twin's byte count —
    a refactor that re-materializes f32 residents fails the audit.
    Returns ``(pred, (params, feed), f32_param_bytes)``."""
    import numpy as np

    import jax

    from paddle_tpu import quant as quant_lib
    from paddle_tpu.config import dsl
    from paddle_tpu.core.network import Network
    from paddle_tpu.data import integer_value, integer_value_sequence
    from paddle_tpu.serving.predictor import (ServingPredictor,
                                              _synth_sample)
    V = 16
    dsl.reset()
    w = dsl.data(name="w", size=V)
    lab = dsl.data(name="label", size=2)
    emb = dsl.embedding(input=w, size=6, name="emb")
    pooled = dsl.pooling(input=emb, pooling_type="avg", name="pool")
    out = dsl.fc(input=pooled, size=2, act="softmax", name="out")
    dsl.classification_cost(input=out, label=lab, name="cost")
    graph = dsl.current_graph()
    params = Network(graph, outputs=["out"]).init_params(
        jax.random.PRNGKey(0))
    params = {k: np.asarray(v) for k, v in params.items()}
    f32_bytes = sum(np.asarray(v).astype(np.float32).nbytes
                    for v in params.values())
    qparams, meta = quant_lib.quantize_params(params, "int8",
                                              sparse_names=set())
    pred = ServingPredictor(
        graph, qparams, ["out"],
        {"w": integer_value_sequence(V), "label": integer_value(2)},
        batch_buckets=[2], length_buckets=[8], donate=True, quant=meta)
    rows = [tuple(_synth_sample(pred.feeding[n], 4)
                  for n in pred.names)] * 2
    feed = pred.feeder(list(rows))
    return pred, (pred.params, feed), f32_bytes


def audit_serving(log=print) -> List[Finding]:
    """The serving warm path: a bucketed scoring predictor's ``_infer``
    (masked sequence model) and a generating predictor's ``_encode``,
    lowered exactly as warmup would compile them (donate=True — the
    TPU/GPU configuration; CPU merely ignores it at run time)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.config import dsl
    from paddle_tpu.core.network import Network
    from paddle_tpu.core.registry import get_layer_impl
    from paddle_tpu.data import dense_vector
    from paddle_tpu.serving.predictor import ServingPredictor, _synth_sample

    anchor = "paddle_tpu/serving/predictor.py"
    findings: List[Finding] = []

    # ---- scoring path (_infer), masked sequence input
    pred, args = build_scoring_predictor()
    closed = jax.make_jaxpr(pred._infer)(*args)
    findings.extend(_const_findings(closed, "serving._infer", anchor))
    dfind, stats = _donation_findings(pred._infer, args, (1,),
                                      "serving._infer", anchor)
    findings.extend(dfind)
    mask_pos = _mask_positions(args)
    if not mask_pos:
        findings.append(Finding(
            "PT203", anchor, 1,
            "serving audit: expected mask leaves in the feed (audit "
            "setup broke)"))
    findings.extend(_mask_findings(closed, mask_pos, "serving._infer",
                                   anchor))
    if log:
        log(f"  serving._infer: donation {stats}, "
            f"{len(mask_pos)} mask leaves traced f32-clean")

    # ---- generation warm path (_encode of a generating config)
    Vg, E, H = 6, 4, 5
    dsl.reset()
    src = dsl.data("src", size=H)
    boot = dsl.fc(src, size=H, act="tanh", name="boot", bias_attr=False)

    def step(prev_emb):
        m = dsl.memory(name="h", size=H, boot_layer=boot)
        h = dsl.fc([prev_emb, m], size=H, act="tanh", name="h",
                   bias_attr=False)
        return dsl.fc(h, size=Vg, act="softmax", name="prob",
                      bias_attr=False)

    dsl.beam_search(
        step, [dsl.GeneratedInput(size=Vg, embedding_name="gen_emb",
                                  embedding_size=E)],
        bos_id=0, eos_id=1, beam_size=2, max_length=4, name="gen")
    ggraph = dsl.current_graph()
    gnet = Network(ggraph, outputs=["boot"])
    gparams = dict(gnet.init_params(jax.random.PRNGKey(0)))
    grng = np.random.RandomState(0)
    for _, spec in get_layer_impl("beam_search_group").params(
            ggraph.layers["gen"], []).items():
        gparams[spec.absolute_name] = jnp.asarray(
            grng.randn(*spec.shape).astype(np.float32) * 0.7)
    gparams["gen_emb"] = jnp.asarray(
        grng.randn(Vg, E).astype(np.float32))
    gpred = ServingPredictor(ggraph, gparams, ["gen"],
                             {"src": dense_vector(H)},
                             batch_buckets=[2], donate=True)
    grows = [tuple(_synth_sample(gpred.feeding[n], 1)
                   for n in gpred.names)] * 2
    gfeed = gpred.feeder(list(grows))
    gargs = (gpred.params, gfeed)
    gclosed = jax.make_jaxpr(gpred._encode)(*gargs)
    findings.extend(_const_findings(gclosed, "serving._encode", anchor))
    dfind, gstats = _donation_findings(gpred._encode, gargs, (1,),
                                       "serving._encode", anchor)
    findings.extend(dfind)
    if log:
        log(f"  serving._encode: donation {gstats}, consts clean")
    return findings


def run_pass2(root: Optional[str] = None, log=print,
              include_entry: bool = True) -> List[Finding]:
    """All trace-time audits. ``include_entry=False`` skips the
    flagship ResNet-50 build (~20 s on the 1-core host) for quick
    iteration; the CLI default runs it.

    ``root`` retargets only the ``__graft_entry__`` import: the
    train-step and serving audits trace the paddle_tpu package THIS
    process imported — a foreign checkout's library code cannot be
    audited without running in that checkout."""
    import os
    findings: List[Finding] = []
    if root is not None and os.path.realpath(root) != os.path.realpath(
            _repo_root()) and log:
        log(f"  NOTE: --root {root} applies to the entry import only; "
            "the train-step/serving audits trace the IMPORTED "
            "paddle_tpu package — run the lint from inside that "
            "checkout to audit its library code")
    findings.extend(audit_train_step(log=log))
    findings.extend(audit_serving(log=log))
    if include_entry:
        findings.extend(audit_entry(log=log, root=root))
    return findings
