"""Shared helpers for the analysis passes (one copy — ast_lints,
lockorder, jaxpr_audit and the CLI must not drift)."""

from __future__ import annotations

import ast
import os
from typing import List, Optional


def dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None (calls,
    subscripts and literals are not simple names)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def repo_root() -> str:
    """The repository root this package lives in (…/paddle_tpu/analysis
    → two packages up)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
