"""Pass 1 — AST invariant lints over paddle_tpu/ + tests/ + tools/.

Each rule is the static twin of a runtime invariant this repo already
enforces (or a convention that so far lived only in CLAUDE.md):

- PT101 jit-closure-capture: params/feeds must be traced jit arguments.
  XLA treats closure captures as program constants; the r10 measurement
  was ~4x/step deopt when the decode step closed over its params
  (core/generation.py:_make_step docstring).
- PT102 mask-bf16-cast: masks are f32 count data
  (trainer/trainer.py:_cast_compute); a bf16 mask saturates at 256.
- PT103 pad-in-bitexact-pack: optim/zero1.py packs with concatenate —
  a jnp.pad fused into the elementwise update breaks XLA:CPU
  bit-exactness. The rule bans jnp.pad in paddle_tpu/optim/ and in any
  function marked ``# graftlint: bit-exact``.
- PT104 unguarded-jit: persistent jits in hot-path modules need a
  RecompileGuard (data/prefetch.py) or a ``# graftlint: jit-cache:``
  note naming the cache policy that bounds them.
- PT105 broad-pkill: ``pkill -f`` with a short/generic pattern matches
  the invoking shell's own command line (the exit-144 self-kill).
- PT106 layer-grad-matrix-row: every ``register_layer`` canonical type
  needs a row in tests/test_layer_grad_matrix.py — the static version
  of test_registry_fully_covered, so the gap is visible at lint time
  (no test collection needed).

Suppression: ``# graftlint: disable=PT101`` (or the rule's short name)
on the flagged line or the line above. Suppressions are counted and
reported; policy in docs/static_analysis.md.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from paddle_tpu.analysis.findings import RULE_BY_NAME, Finding

# ---------------------------------------------------------------- config

# PT104 scope: modules whose jitted callables sit on a request/step hot
# path. Library builders (parallel/moe.py, parallel/pipeline.py,
# core/network.py init) hand the jit to a caller who owns cache policy
# and are deliberately out of scope — see docs/static_analysis.md.
HOT_PATH_MODULES = (
    "paddle_tpu/trainer/trainer.py",
    "paddle_tpu/serving/",
    "paddle_tpu/core/generation.py",
    "paddle_tpu/models/",
    "paddle_tpu/compat/swig_api.py",
)

# PT101: names that conventionally bind batch/param arrays in this repo.
ARRAYISH_NAMES = {
    "feed", "feeds", "feed_dict", "params", "tparams", "nparams",
    "pparams", "batch", "weights", "noise", "grads", "mask", "masks",
    "opt_state",
}
ARRAYISH_SUFFIXES = ("_feed", "_params", "_batch", "_mask")

# PT101: calls whose result is (or contains) device/numpy arrays.
_ARRAY_CALL_EXACT = {
    "jax.device_put", "jax.device_get", "np.asarray", "np.array",
    "np.ones", "np.zeros", "np.full", "numpy.asarray", "numpy.array",
}
_ARRAY_CALL_PREFIX = ("jnp.", "jax.numpy.", "jax.random.")
_ARRAY_CALL_SUFFIX = (".shard_batch",)

_SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\- ]+)")
_JIT_CACHE_RE = re.compile(r"#\s*graftlint:\s*jit-cache:")
_BIT_EXACT_RE = re.compile(r"#\s*graftlint:\s*bit-exact")

_LOW_DTYPES = ("bfloat16", "float16", "bf16", "f16", "half")


from paddle_tpu.analysis._astutil import dotted as _dotted


def _is_array_call(node: ast.AST) -> bool:
    """Does this expression produce an array (recursively through
    IfExp/BinOp/BoolOp shells)?"""
    if isinstance(node, ast.IfExp):
        return _is_array_call(node.body) or _is_array_call(node.orelse)
    if isinstance(node, ast.BinOp):
        return _is_array_call(node.left) or _is_array_call(node.right)
    if isinstance(node, ast.BoolOp):
        return any(_is_array_call(v) for v in node.values)
    if not isinstance(node, ast.Call):
        return False
    d = _dotted(node.func)
    if d is None:
        return False
    if d in _ARRAY_CALL_EXACT:
        return True
    if d.startswith(_ARRAY_CALL_PREFIX):
        return True
    if any(d.endswith(s) for s in _ARRAY_CALL_SUFFIX):
        return True
    if d.endswith(".astype"):
        return True
    return False


def _arrayish_name(name: str) -> bool:
    return (name in ARRAYISH_NAMES
            or any(name.endswith(s) for s in ARRAYISH_SUFFIXES))


def _name_targets(tgt: ast.AST) -> List[str]:
    """Plain names BOUND by an assignment target. A Name inside an
    Attribute/Subscript target (``self.x = ...``) is a *load* of the
    base object, not a binding of that name — walking it naively makes
    ``self`` look array-bound the first time ``self.rng = PRNGKey(...)``
    appears."""
    if isinstance(tgt, ast.Name):
        return [tgt.id]
    if isinstance(tgt, (ast.Tuple, ast.List)):
        out: List[str] = []
        for elt in tgt.elts:
            out.extend(_name_targets(elt))
        return out
    if isinstance(tgt, ast.Starred):
        return _name_targets(tgt.value)
    return []


class _Scope:
    """One function (or module) scope: names it binds, and the assign
    RHS nodes per name (for array-likeness checks)."""

    def __init__(self, node: ast.AST, parent: Optional["_Scope"]):
        self.node = node
        self.parent = parent
        self.bound: Set[str] = set()
        self.assigns: Dict[str, List[ast.AST]] = {}

    @property
    def is_function(self) -> bool:
        return isinstance(self.node,
                          (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.Lambda))

    def bind(self, name: str, rhs: Optional[ast.AST] = None):
        self.bound.add(name)
        if rhs is not None:
            self.assigns.setdefault(name, []).append(rhs)


def _bound_names(fn: ast.AST) -> Set[str]:
    """Names bound inside a function body (args, assignments, defs,
    imports, loop/with/comprehension targets) — NOT descending into
    nested function bodies' own locals is unnecessary for free-variable
    math: a name bound anywhere inside the subtree is not free."""
    bound: Set[str] = set()

    class V(ast.NodeVisitor):
        def visit_arg(self, node):
            bound.add(node.arg)

        def visit_Name(self, node):
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                bound.add(node.id)

        def visit_FunctionDef(self, node):
            bound.add(node.name)
            self.generic_visit(node)

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_ClassDef(self, node):
            bound.add(node.name)
            self.generic_visit(node)

        def visit_Import(self, node):
            for a in node.names:
                bound.add((a.asname or a.name).split(".")[0])

        visit_ImportFrom = visit_Import

    v = V()
    if isinstance(fn, ast.Lambda):
        for a in (fn.args.args + fn.args.posonlyargs + fn.args.kwonlyargs):
            bound.add(a.arg)
        if fn.args.vararg:
            bound.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            bound.add(fn.args.kwarg.arg)
        v.visit(fn.body)
    else:
        for a in (fn.args.args + fn.args.posonlyargs + fn.args.kwonlyargs):
            bound.add(a.arg)
        if fn.args.vararg:
            bound.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            bound.add(fn.args.kwarg.arg)
        for stmt in fn.body:
            v.visit(stmt)
    return bound


def _free_loads(fn: ast.AST) -> List[ast.Name]:
    bound = _bound_names(fn)
    loads: List[ast.Name] = []
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        for node in ast.walk(stmt):
            if (isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id not in bound):
                loads.append(node)
    return loads


class FileLinter:
    """All Pass-1 rules over one source file."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.findings: List[Finding] = []
        self.suppressed = 0
        self._scopes: List[_Scope] = []
        self._module_scope = _Scope(self.tree, None)
        # one child->parent map per file; several rules consult it
        self._parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self._parents[child] = node

    # -------------------------------------------------- suppressions
    def _annotation_lines(self, line: int):
        """The flagged line plus the contiguous comment block above it
        (suppressions/policy notes may need more than one line)."""
        if 1 <= line <= len(self.lines):
            yield self.lines[line - 1]
        ln = line - 1
        while ln >= 1 and self.lines[ln - 1].lstrip().startswith("#"):
            yield self.lines[ln - 1]
            ln -= 1

    def _suppressed_rules(self, line: int) -> Set[str]:
        out: Set[str] = set()
        for text in self._annotation_lines(line):
            m = _SUPPRESS_RE.search(text)
            if m:
                for tok in re.split(r"[,\s]+", m.group(1).strip()):
                    if not tok:
                        continue
                    out.add(RULE_BY_NAME.get(tok, tok))
        return out

    def _emit(self, rule: str, line: int, msg: str):
        if rule in self._suppressed_rules(line):
            self.suppressed += 1
            return
        self.findings.append(Finding(rule, self.rel, line, msg))

    def _line_has(self, line: int, regex) -> bool:
        return any(regex.search(text)
                   for text in self._annotation_lines(line))

    # ------------------------------------------------------ driving
    def run(self) -> List[Finding]:
        self._collect_scopes()
        self._lint_jit_sites()
        self._lint_mask_casts()
        self._lint_pad_bitexact()
        self._lint_pkill()
        return self.findings

    # ------------------------------------------- scope bookkeeping
    def _collect_scopes(self):
        """Map every function node to its scope object + parent chain,
        and record assignments per scope (for PT101 binding lookups)."""
        self.scope_of: Dict[ast.AST, _Scope] = {}

        def walk(node: ast.AST, scope: _Scope):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    sub = _Scope(child, scope)
                    self.scope_of[child] = sub
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        scope.bind(child.name)
                    # the function's PARAMETERS are bindings of its
                    # scope: a jitted inner function capturing an
                    # enclosing function's `feed`/`params` ARGUMENT is
                    # the canonical PT101 shape and must resolve to a
                    # function scope, not fall through as a global
                    a = child.args
                    for arg in (a.args + a.posonlyargs + a.kwonlyargs):
                        sub.bind(arg.arg)
                    if a.vararg:
                        sub.bind(a.vararg.arg)
                    if a.kwarg:
                        sub.bind(a.kwarg.arg)
                    walk(child, sub)
                    continue
                if isinstance(child, ast.ClassDef):
                    scope.bind(child.name)
                    # class body: functions inside still close over the
                    # enclosing FUNCTION scope, not the class scope
                    walk(child, scope)
                    continue
                if isinstance(child, ast.Assign):
                    for tgt in child.targets:
                        for n in _name_targets(tgt):
                            scope.bind(n, child.value)
                elif isinstance(child, ast.AnnAssign):
                    if isinstance(child.target, ast.Name):
                        scope.bind(child.target.id, child.value)
                elif isinstance(child, ast.AugAssign):
                    if isinstance(child.target, ast.Name):
                        scope.bind(child.target.id, child.value)
                elif isinstance(child, (ast.For, ast.AsyncFor)):
                    for n in _name_targets(child.target):
                        scope.bind(n, child.iter)
                elif isinstance(child, (ast.With, ast.AsyncWith)):
                    for item in child.items:
                        if item.optional_vars is not None:
                            for n in _name_targets(item.optional_vars):
                                scope.bind(n, item.context_expr)
                elif isinstance(child, (ast.Import, ast.ImportFrom)):
                    for a in child.names:
                        scope.bind((a.asname or a.name).split(".")[0])
                walk(child, scope)

        self.scope_of[self.tree] = self._module_scope
        walk(self.tree, self._module_scope)

    # --------------------------------------------------- PT101/PT104
    def _jitted_functions(self) -> List[Tuple[ast.AST, ast.AST, bool]]:
        """(function-node, report-node, persistent?) for every jit site.

        persistent = the jitted callable outlives the statement (bound
        to a name/attribute or returned), as opposed to
        ``jax.jit(f)(x)`` one-shots.
        """
        out: List[Tuple[ast.AST, ast.AST, bool]] = []
        parents = self._parents

        def local_fn(name: str, at: ast.AST) -> Optional[ast.AST]:
            """Resolve a Name to a FunctionDef/Lambda in the scope
            chain of the jit call site."""
            scope = self._enclosing_scope(at)
            while scope is not None:
                if name in scope.assigns:
                    for rhs in scope.assigns[name]:
                        if isinstance(rhs, ast.Lambda):
                            return rhs
                # sibling def in the scope's body
                body = getattr(scope.node, "body", [])
                if isinstance(body, list):
                    for stmt in body:
                        if (isinstance(stmt, (ast.FunctionDef,
                                              ast.AsyncFunctionDef))
                                and stmt.name == name):
                            return stmt
                scope = scope.parent
            return None

        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    d = _dotted(dec)
                    dc = _dotted(dec.func) if isinstance(dec, ast.Call) \
                        else None
                    if d in ("jax.jit", "jit", "pjit", "jax.pjit") or (
                            dc in ("functools.partial", "partial")
                            and isinstance(dec, ast.Call) and dec.args
                            and _dotted(dec.args[0]) in (
                                "jax.jit", "jit", "pjit", "jax.pjit")):
                        out.append((node, node, True))
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d not in ("jax.jit", "jit", "pjit", "jax.pjit"):
                    continue
                parent = parents.get(node)
                persistent = not (isinstance(parent, ast.Call)
                                  and parent.func is node)
                fn_node: Optional[ast.AST] = None
                if node.args:
                    arg0 = node.args[0]
                    if isinstance(arg0, ast.Lambda):
                        fn_node = arg0
                    elif isinstance(arg0, ast.Name):
                        fn_node = local_fn(arg0.id, node)
                out.append((fn_node, node, persistent))
        return out

    def _enclosing_scope(self, node: ast.AST) -> _Scope:
        """Nearest function scope containing ``node`` (by position)."""
        best = self._module_scope
        best_span = None
        for fn, scope in self.scope_of.items():
            if fn is self.tree:
                continue
            if (hasattr(fn, "lineno")
                    and fn.lineno <= node.lineno
                    and node.lineno <= (fn.end_lineno or fn.lineno)):
                span = (fn.end_lineno or fn.lineno) - fn.lineno
                if best_span is None or span < best_span:
                    best, best_span = scope, span
        return best

    def _lint_jit_sites(self):
        guard_args = self._recompile_guard_args()
        for fn_node, report, persistent in self._jitted_functions():
            line = report.lineno
            # ------------------------------------------------ PT101
            if fn_node is not None:
                scope = self.scope_of.get(
                    fn_node, self._enclosing_scope(fn_node))
                flagged: Set[str] = set()
                for load in _free_loads(fn_node):
                    name = load.id
                    if name in flagged:
                        continue
                    binding_scope = scope.parent if scope else None
                    s = binding_scope
                    while s is not None and name not in s.bound:
                        s = s.parent
                    if s is None or not s.is_function:
                        continue  # global/builtin: config, nets, modules
                    rhs_list = s.assigns.get(name, [])
                    arrayish = any(_is_array_call(r) for r in rhs_list
                                   if r is not None)
                    if arrayish or _arrayish_name(name):
                        flagged.add(name)
                        # a disable on the jitted function's def line
                        # silences too (the jit call may sit far away)
                        if hasattr(fn_node, "lineno") and "PT101" in \
                                self._suppressed_rules(fn_node.lineno):
                            self.suppressed += 1
                            continue
                        self._emit(
                            "PT101", line,
                            f"jitted function closure-captures {name!r} "
                            "(bound in an enclosing function scope to "
                            "an array-like value); XLA embeds closure "
                            "captures as program constants — pass it as "
                            "a traced argument")
            # ------------------------------------------------ PT104
            if (persistent
                    and any(self.rel.startswith(m) or self.rel == m
                            for m in HOT_PATH_MODULES)):
                if self._line_has(line, _JIT_CACHE_RE):
                    continue
                target = self._jit_target_text(report)
                if target is not None and target in guard_args:
                    continue
                self._emit(
                    "PT104", line,
                    "persistent jax.jit in a hot-path module with no "
                    "RecompileGuard registration"
                    + (f" for {target!r}" if target else "")
                    + " and no '# graftlint: jit-cache:' policy note")

    def _jit_target_text(self, report: ast.AST) -> Optional[str]:
        """Where does this jit land? Assignment target text, the
        function's own name (decorator form), or — for ``return
        jax.jit(...)`` inside a builder method — the attribute that the
        builder's result is assigned to (resolved through one level of
        ``return self._build_x()`` chaining)."""
        if isinstance(report, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return report.name
        parents = self._parents
        p = parents.get(report)
        while p is not None and not isinstance(
                p, (ast.Assign, ast.Return, ast.FunctionDef,
                    ast.AsyncFunctionDef, ast.Module)):
            p = parents.get(p)
        if isinstance(p, ast.Assign) and len(p.targets) == 1:
            return ast.unparse(p.targets[0])
        if isinstance(p, ast.Return):
            # builder method: find what its call result is assigned to,
            # following `return self.other_builder()` one hop
            meth = parents.get(p)
            while meth is not None and not isinstance(
                    meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                meth = parents.get(meth)
            if meth is None:
                return None
            names = {meth.name}
            for _ in range(3):  # bounded chaining
                grew = False
                for node in ast.walk(self.tree):
                    if (isinstance(node, ast.Return)
                            and isinstance(node.value, ast.Call)):
                        d = _dotted(node.value.func) or ""
                        if d.split(".")[-1] in names:
                            m = parents.get(node)
                            while m is not None and not isinstance(
                                    m, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                                m = parents.get(m)
                            if m is not None and m.name not in names:
                                names.add(m.name)
                                grew = True
                if not grew:
                    break
            for node in ast.walk(self.tree):
                if (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)):
                    d = _dotted(node.value.func) or ""
                    if d.split(".")[-1] in names \
                            and len(node.targets) == 1:
                        return ast.unparse(node.targets[0])
        return None

    def _recompile_guard_args(self) -> Set[str]:
        """First-argument texts of every RecompileGuard(...) call in the
        file — the set of 'registered' jit targets."""
        out: Set[str] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                d = _dotted(node.func) or ""
                if d.split(".")[-1] == "RecompileGuard" and node.args:
                    out.add(ast.unparse(node.args[0]))
        return out

    # ------------------------------------------------------- PT102
    def _lint_mask_casts(self):
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            recv_text = None
            args_text = ""
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "astype"):
                recv_text = ast.unparse(node.func.value)
                args_text = " ".join(
                    ast.unparse(a) for a in node.args) + " ".join(
                    ast.unparse(k.value) for k in node.keywords)
            else:
                d = _dotted(node.func) or ""
                if d in ("jnp.asarray", "jnp.array", "jax.numpy.asarray",
                         "jax.numpy.array") and node.args:
                    recv_text = ast.unparse(node.args[0])
                    args_text = " ".join(
                        ast.unparse(k.value) for k in node.keywords
                        if k.arg == "dtype")
                    args_text += " ".join(ast.unparse(a)
                                          for a in node.args[1:])
            if recv_text is None:
                continue
            if not re.search(r"mask", recv_text, re.IGNORECASE):
                continue
            if any(t in args_text for t in _LOW_DTYPES):
                self._emit(
                    "PT102", node.lineno,
                    f"mask expression {recv_text!r} cast to a sub-f32 "
                    "dtype; masks are f32 count data (bf16 saturates at "
                    "256) — see trainer/trainer.py:_cast_compute")

    # ------------------------------------------------------- PT103
    def _lint_pad_bitexact(self):
        in_optim = "/optim/" in ("/" + self.rel)
        marked_spans: List[Tuple[int, int]] = []
        if not in_optim:
            for node in ast.walk(self.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    # the marker sits on the def line, the line above
                    # it, or anywhere inside the function's first lines
                    for ln in range(max(1, node.lineno - 1),
                                    min(node.lineno + 2,
                                        len(self.lines) + 1)):
                        if _BIT_EXACT_RE.search(self.lines[ln - 1]):
                            marked_spans.append(
                                (node.lineno,
                                 node.end_lineno or node.lineno))
                            break
            if not marked_spans:
                return
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func) or ""
            if d not in ("jnp.pad", "jax.numpy.pad"):
                continue
            hit = in_optim or any(a <= node.lineno <= b
                                  for a, b in marked_spans)
            if hit:
                self._emit(
                    "PT103", node.lineno,
                    "jnp.pad in a bit-exact pack path; XLA:CPU fuses "
                    "the pad into downstream elementwise math and "
                    "rounds real elements differently — pack with "
                    "concatenate/slices (optim/zero1.py:_pack)")

    # ------------------------------------------------------- PT105
    _EXEC_CALLS = {
        "os.system", "os.popen", "subprocess.run", "subprocess.call",
        "subprocess.Popen", "subprocess.check_call",
        "subprocess.check_output", "subprocess.getoutput",
    }

    def _lint_pkill(self):
        """In Python sources only string arguments of exec-style calls
        are shell commands — scanning every line would flag docstrings
        that merely *mention* pkill (including this linter's own)."""
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func) or ""
            if d not in self._EXEC_CALLS:
                continue
            for arg in list(node.args) + [k.value for k in node.keywords]:
                for sub in ast.walk(arg):
                    if not (isinstance(sub, ast.Constant)
                            and isinstance(sub.value, str)):
                        continue
                    for m in re.finditer(_PKILL_RE, sub.value):
                        if self._pkill_broad(m.group(2)):
                            self._emit(
                                "PT105", sub.lineno,
                                f"broad `pkill -f {m.group(2)}` — the "
                                "-f pattern matches your own shell's "
                                "command string (exit-144 self-kill); "
                                "use a narrow, command-specific "
                                "pattern")

    @staticmethod
    def _pkill_broad(pattern: str) -> bool:
        generic = {"python", "python3", "pytest", "jax", "bench",
                   "nohup", "bash", "sh", "timeout"}
        stripped = pattern.strip("'\"")
        if stripped.lower() in generic:
            return True
        return len(stripped) < 12


_PKILL_RE = r"pkill\s+(?:-\w+\s+)*-f\s+(['\"]?)([^'\"\s;|&]+)\1"


# ----------------------------------------------------- shell-file rule
def lint_shell_file(path: str, rel: str, source: str) -> List[Finding]:
    """PT105 over shell scripts (no AST; line scan)."""
    findings: List[Finding] = []
    for i, line in enumerate(source.splitlines(), 1):
        if _SUPPRESS_RE.search(line):
            continue
        if line.lstrip().startswith("#"):
            continue
        for m in re.finditer(_PKILL_RE, line):
            if FileLinter._pkill_broad(m.group(2)):
                findings.append(Finding(
                    "PT105", rel.replace(os.sep, "/"), i,
                    f"broad `pkill -f {m.group(2)}` in a shell tool — "
                    "narrow the pattern (it matches the invoking "
                    "shell's own command string)"))
    return findings


# -------------------------------------------------------------- PT107
_CHAOS_REL = "paddle_tpu/testing/chaos.py"
_FLIGHT_MATRIX_REL = "tests/test_obs_flight.py"


def _hit_sites_from_tree(tree: ast.Module) -> List[Tuple[str, int]]:
    """(site-name, line) per ``_chaos._ACTIVE.hit("<site>", ...)`` call
    — the canonical production spelling (the receiver must end in
    ``_ACTIVE``, so a test's ``plan.hit(...)`` never counts)."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "hit"):
            continue
        recv = _dotted(node.func.value) or ""
        if not recv.endswith("_ACTIVE"):
            continue
        if node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            out.append((node.args[0].value, node.lineno))
    return out


def _sites_from_tree(tree: ast.Module
                     ) -> Tuple[Optional[Set[str]], int]:
    """chaos.py's declared ``SITES`` tuple (None when missing)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "SITES" \
                and isinstance(node.value, (ast.Tuple, ast.List)):
            sites = {e.value for e in node.value.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str)}
            return sites, node.lineno
    return None, 1


def _site_cases_from_tree(tree: ast.Module) -> Optional[Set[str]]:
    """The flight matrix's ``SITE_CASES`` dict keys (None = absent)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "SITE_CASES" \
                and isinstance(node.value, ast.Dict):
            return {k.value for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
    return None


def _chaos_site_findings(hits: Dict[str, Tuple[str, int]],
                         chaos_tree: Optional[ast.Module],
                         matrix_tree: Optional[ast.Module]
                         ) -> List[Finding]:
    """PT107: every .hit site declared in chaos.SITES; every declared
    site exercised by the closure-enforced flight matrix AND by at
    least one production hit (a dead declaration is drift too)."""
    if chaos_tree is None:
        return [Finding("PT107", _CHAOS_REL, 1,
                        "chaos module missing/unparsed — chaos-site "
                        "coverage cannot be checked")]
    sites, sites_line = _sites_from_tree(chaos_tree)
    if sites is None:
        return [Finding("PT107", _CHAOS_REL, 1,
                        "chaos.SITES catalog missing — declare the "
                        "closed set of hook sites")]
    findings: List[Finding] = []
    for site, (rel, line) in sorted(hits.items()):
        if site not in sites:
            findings.append(Finding(
                "PT107", rel, line,
                f"chaos site {site!r} fired here but is not declared "
                "in chaos.SITES — declare it (and add its "
                "tests/test_obs_flight.py SITE_CASES row) so the "
                "flight-recorder matrix and the docs cover it"))
    cases = (_site_cases_from_tree(matrix_tree)
             if matrix_tree is not None else None)
    if cases is None:
        findings.append(Finding(
            "PT107", _FLIGHT_MATRIX_REL, 1,
            "flight-recorder matrix (SITE_CASES) missing — every "
            "chaos.SITES member needs a firing row proving it emits "
            "its flight event"))
    else:
        for site in sorted(sites - cases):
            findings.append(Finding(
                "PT107", _CHAOS_REL, sites_line,
                f"chaos site {site!r} declared without a firing row "
                "in tests/test_obs_flight.py:SITE_CASES — a site "
                "without its matrix row ships without its postmortem "
                "event"))
    for site in sorted(sites - set(hits)):
        findings.append(Finding(
            "PT107", _CHAOS_REL, sites_line,
            f"chaos site {site!r} declared in chaos.SITES but no "
            "_chaos._ACTIVE.hit(...) in paddle_tpu/ fires it — dead "
            "declaration (remove it, with its matrix row)"))
    return findings


def lint_chaos_sites(root: str) -> List[Finding]:
    """Standalone PT107 (fixture tests use this directly); the repo
    driver aggregates from run_pass1's already-parsed trees."""
    hits: Dict[str, Tuple[str, int]] = {}
    pkg = os.path.join(root, "paddle_tpu")
    chaos_tree = None
    for dirpath, _dirs, files in os.walk(pkg):
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            try:
                tree = ast.parse(open(path, encoding="utf-8").read(),
                                 filename=path)
            except (SyntaxError, OSError):
                continue
            if rel == _CHAOS_REL:
                chaos_tree = tree
            for site, line in _hit_sites_from_tree(tree):
                hits.setdefault(site, (rel, line))
    matrix_path = os.path.join(root, _FLIGHT_MATRIX_REL)
    matrix_tree = None
    if os.path.exists(matrix_path):
        try:
            matrix_tree = ast.parse(
                open(matrix_path, encoding="utf-8").read(),
                filename=matrix_path)
        except SyntaxError:
            matrix_tree = None
    return _chaos_site_findings(hits, chaos_tree, matrix_tree)


# -------------------------------------------------------------- PT106
def _registrations_from_tree(tree: ast.Module) -> List[Tuple[str, int]]:
    """(canonical-type-name, line) per register_layer decorator."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for dec in node.decorator_list:
            if (isinstance(dec, ast.Call)
                    and (_dotted(dec.func) or "").split(".")[-1]
                    == "register_layer" and dec.args
                    and isinstance(dec.args[0], ast.Constant)):
                out.append((dec.args[0].value, dec.lineno))
    return out


def _covered_from_tree(mtree: ast.Module) -> Set[str]:
    covered: Set[str] = set()
    for node in ast.walk(mtree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tname = ast.unparse(node.targets[0])
            if tname in ("GRAD_CASES", "FWD_CASES", "COVERED_ELSEWHERE") \
                    and isinstance(node.value, ast.Dict):
                for k in node.value.keys:
                    if isinstance(k, ast.Constant) \
                            and isinstance(k.value, str):
                        covered.add(k.value)
    return covered


_MATRIX_REL = "tests/test_layer_grad_matrix.py"


def _matrix_findings(registered: Dict[str, Tuple[str, int]],
                     mtree: Optional[ast.Module]) -> List[Finding]:
    if mtree is None:
        return [Finding("PT106", _MATRIX_REL, 1, "matrix file missing")]
    covered = _covered_from_tree(mtree)
    findings: List[Finding] = []
    for canonical, (rel, line) in sorted(registered.items()):
        if canonical not in covered:
            findings.append(Finding(
                "PT106", rel.replace(os.sep, "/"), line,
                f"layer type {canonical!r} registered without a row in "
                "tests/test_layer_grad_matrix.py (GRAD_CASES / "
                "FWD_CASES / COVERED_ELSEWHERE)"))
    return findings


def lint_layer_matrix(root: str) -> List[Finding]:
    """Standalone PT106 (fixture tests use this directly); the repo
    driver collects registrations from run_pass1's already-parsed
    trees instead of re-walking."""
    registered: Dict[str, Tuple[str, int]] = {}
    pkg = os.path.join(root, "paddle_tpu")
    for dirpath, _dirs, files in os.walk(pkg):
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            try:
                tree = ast.parse(open(path, encoding="utf-8").read(),
                                 filename=path)
            except SyntaxError:
                continue
            for canonical, line in _registrations_from_tree(tree):
                registered.setdefault(
                    canonical, (os.path.relpath(path, root), line))
    matrix_path = os.path.join(root, _MATRIX_REL)
    mtree = None
    if os.path.exists(matrix_path):
        mtree = ast.parse(open(matrix_path, encoding="utf-8").read(),
                          filename=matrix_path)
    return _matrix_findings(registered, mtree)


# ------------------------------------------------------------- driver
def _iter_source_files(root: str,
                       subdirs: Sequence[str] = ("paddle_tpu", "tests",
                                                 "tools")):
    for sub in subdirs:
        base = os.path.join(root, sub)
        if os.path.isfile(base):
            yield base
            continue
        for dirpath, dirs, files in os.walk(base):
            dirs[:] = [d for d in dirs
                       if d not in ("__pycache__", "proto")]
            for fname in sorted(files):
                if fname.endswith((".py", ".sh")):
                    yield os.path.join(dirpath, fname)
    extra = os.path.join(root, "bench.py")
    if os.path.exists(extra):
        yield extra


def run_pass1(root: str,
              paths: Optional[Sequence[str]] = None
              ) -> Tuple[List[Finding], int]:
    """(findings, suppressed-count) over the repo (or explicit paths)."""
    findings: List[Finding] = []
    suppressed = 0
    # PT106/PT107 ride the same parse: registrations, chaos hit sites,
    # and the matrix trees are collected from the linters' ASTs
    # (re-walking the package would double the fast lint's parse work)
    registered: Dict[str, Tuple[str, int]] = {}
    hit_sites: Dict[str, Tuple[str, int]] = {}
    matrix_tree: Optional[ast.Module] = None
    chaos_tree: Optional[ast.Module] = None
    flight_matrix_tree: Optional[ast.Module] = None
    files = list(paths) if paths else list(_iter_source_files(root))
    for path in files:
        rel = os.path.relpath(path, root)
        try:
            source = open(path, encoding="utf-8").read()
        except (OSError, UnicodeDecodeError):
            continue
        if path.endswith(".sh"):
            findings.extend(lint_shell_file(path, rel, source))
            continue
        try:
            linter = FileLinter(path, rel, source)
        except SyntaxError as e:
            # own rule id: a parse failure must never be swallowed by
            # a PT101 baseline/disable entry for unrelated findings
            findings.append(Finding("PT100", rel, e.lineno or 1,
                                    f"unparseable source: {e.msg}"))
            continue
        findings.extend(linter.run())
        suppressed += linter.suppressed
        if linter.rel == _MATRIX_REL:
            matrix_tree = linter.tree
        elif linter.rel == _FLIGHT_MATRIX_REL:
            flight_matrix_tree = linter.tree
        elif linter.rel.startswith("paddle_tpu/"):
            if linter.rel == _CHAOS_REL:
                chaos_tree = linter.tree
            for canonical, line in _registrations_from_tree(
                    linter.tree):
                registered.setdefault(canonical, (linter.rel, line))
            for site, line in _hit_sites_from_tree(linter.tree):
                hit_sites.setdefault(site, (linter.rel, line))
    if paths is None:
        findings.extend(_matrix_findings(registered, matrix_tree))
        findings.extend(_chaos_site_findings(hit_sites, chaos_tree,
                                             flight_matrix_tree))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, suppressed
