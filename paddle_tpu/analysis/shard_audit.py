"""Pass 4 — sharding & collective-communication audit of the real
parallel programs.

ROADMAP item 1 (the unified 4D ``data x fsdp x tp x pipe`` layout) will
refactor every parallel program in this repo, and nothing machine-checks
what those programs actually *communicate*: the zero1
``with_sharding_constraint`` pins and the pipeline ``P(pipe)`` rules are
conventions a refactor can silently break (the r07 incident —
propagation rewrote the backward ~2x slower when the fused buffers were
left unpinned). Same premise as pass 2: jitted JAX gives us static
graphs, so audit the *lowered program*, not the source — but one level
deeper: pass 4 runs the SPMD partitioner (``.lower().compile()`` on the
8-device virtual mesh) and reads the optimized HLO, because the
collectives that cost real ICI time only exist after partitioning.

Traced programs (kept deliberately tiny — the op *structure* is what the
manifest pins, and XLA emits the same collective program for a 12-wide
fc as for a 12288-wide one):

- ``dp_train``   — the plain data-parallel train step (grad all-reduce).
- ``zero1``      — ZeRO-1 sharded optimizer step (the ONE fused
  all-gather + the pinned pack buffers, ``optim/zero1.py``).
- ``pipeline``   — the GPipe shard_map'd scan (stage-handoff
  collective-permutes + pipe-axis psum, composed with the data axis).
- ``tp_embed``   — tensor parallelism: a model-axis row-sharded
  embedding table through a full train step.
- ``seq_ring``   — ring attention fwd+bwd over the seq axis
  (``parallel/ring.py`` ppermute ring).
- ``fsdp_train`` — full FSDP: parameters flat-packed 1/8 over the
  fsdp axis, ONE all-gather per layer on use, gradients reduced back
  into the packed layout (``optim/zero1.py:FsdpUpdater``).
- ``fsdp_pipe``  — the composed plane: stage-stacked body over pipe +
  fsdp-packed head, both plans derived from one SpecLayout table.
- ``serving_warm`` — the serving warm path; its manifest is pinned
  EMPTY (serving must never grow a collective).

Checks:

- **PT501 collective budget**: every ``all-reduce`` / ``all-gather`` /
  ``reduce-scatter`` / ``collective-permute`` / ``all-to-all`` in the
  optimized HLO, counted per (program, op, mesh-axis) with byte volume,
  must match ``analysis/comm_budget.toml`` exactly. Counts are static
  program-text sites (an op inside a scan body counts once). Growth is
  drift; shrinkage means the budget must be tightened (the only-shrinks
  policy of baseline.toml, applied to communication).
- **PT502 unintended replication**: a large (> ``BIG_BYTES``) parameter
  or optimizer slot in a program's must-shard contract whose *placed*
  sharding is fully replicated despite a mesh axis that divides it.
- **PT503 unpinned pack**: a shard_map operand with a sharded in_spec
  built by a pack op (``concatenate``/``pad``) with no
  ``with_sharding_constraint`` between the pack and the shard_map —
  the exact r07 backward-rewrite class.
- **PT504 reshard copy**: two conflicting sharding constraints on the
  same value chain inside one program (each transition is a real
  device-to-device copy on TPU).
- **PT505 rule-table hygiene** (``parallel/mesh.py:rule_for``): dead
  keys matching no parameter, ``=``-exact keys that exact-match
  nothing, and keys shadowed by an earlier match on every name they
  cover — checked against the rule tables the traced programs actually
  construct (trainer shard_rules, pipeline plan rules).

Heavy imports (jax, trainers, model builders) stay inside functions:
pass 1/3 and ``--fast`` must not pay them.
"""

from __future__ import annotations

import os
import re
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from paddle_tpu.analysis.findings import Finding

# a leaf below this is scaffolding, not model state — same rationale as
# jaxpr_audit.CONST_LIMIT_BYTES
BIG_BYTES = 64 * 1024

COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                  "collective-permute", "all-to-all")

# ops PT503/PT504 chains look *through* (value-preserving): shape-only
# ops plus dtype casts
_THROUGH_OPS = {
    "reshape", "broadcast_in_dim", "squeeze", "expand_dims",
    "transpose", "slice", "dynamic_slice", "copy", "rev",
    "convert_element_type",
}
_PACK_OPS = {"concatenate", "pad"}

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2,
    "bf16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
    "f64": 8, "c64": 8, "c128": 16,
}


# ============================================================ comm budget
def default_budget_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "comm_budget.toml")


class BudgetEntry:
    __slots__ = ("program", "op", "axis", "ops", "bytes")

    def __init__(self):
        self.program = ""
        self.op = ""
        self.axis = ""
        self.ops = 0
        self.bytes = 0

    def key(self) -> Tuple[str, str, str]:
        return (self.program, self.op, self.axis)


def load_budget(path: Optional[str] = None) -> List[BudgetEntry]:
    """Parse ``comm_budget.toml`` (the shared TOML-subset table parser
    from baseline.py — the py3.10 container has no tomllib)."""
    from paddle_tpu.analysis.baseline import parse_toml_tables
    path = path or default_budget_path()
    if not os.path.exists(path):
        return []
    entries = parse_toml_tables(
        path, "comm budget", "[[collective]]", BudgetEntry,
        int_keys=("ops", "bytes"), str_keys=("program", "op", "axis"))
    seen: Dict[Tuple[str, str, str], int] = {}
    for e in entries:
        if not e.program or not e.op or not e.axis:
            raise ValueError(
                f"comm budget {path}: every [[collective]] needs "
                "program=, op= and axis=")
        if e.ops < 1 or e.bytes < 1:
            # pinning zero sites is spelled by ABSENCE of the entry;
            # a missing/zero ops= or bytes= would otherwise surface as
            # a baffling 'GREW past its budget 0 / 0' drift report
            raise ValueError(
                f"comm budget {path}: entry (program={e.program} "
                f"op={e.op} axis={e.axis!r}) needs ops= and bytes= "
                ">= 1 (zero is pinned by deleting the entry)")
        if e.key() in seen:
            raise ValueError(
                f"comm budget {path}: duplicate entry for "
                f"(program={e.program} op={e.op} axis={e.axis!r}) — "
                "merge-conflict leftovers would silently resolve to "
                "the last one")
        seen[e.key()] = 1
    return entries


# ====================================================== manifest (HLO side)
_SHAPE_RE = re.compile(r"([a-z]+\d*)\[([\d,]*)\]")
_OP_RE = re.compile(
    r"=\s*(\([^)]*\)|\S+)\s+"
    r"(all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)(-start)?\(")
_GROUPS_RE = re.compile(r"replica_groups=\{(\{[\d,{} ]*\})\}")
_IOTA_GROUPS_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?")
_PAIRS_RE = re.compile(r"source_target_pairs=\{([\d,{} ]*)\}")


def _shape_bytes(shape_txt: str, async_start: bool = False) -> int:
    """Payload bytes of an HLO result shape (tuple shapes sum). An
    async ``-start`` op's result tuple carries BOTH the operand and
    output buffers — count only the output half, so the same
    collective budgets identically whichever spelling XLA picks."""
    elems = []
    for dtype, dims in _SHAPE_RE.findall(shape_txt):
        width = _DTYPE_BYTES.get(dtype)
        if width is None:
            continue  # token/opaque — carries no payload
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        elems.append(n * width)
    if async_start and len(elems) > 1:
        elems = elems[len(elems) // 2:]
    return sum(elems)


def _mesh_axis_groups(mesh) -> Dict[str, frozenset]:
    """{axis-label: groups} for every non-trivial combination of mesh
    axes, as frozensets of frozensets of *device ids* (the compiled
    HLO's ``use_global_device_ids`` currency). Combination labels join
    axis names with ``+`` in mesh order."""
    import itertools

    import numpy as np
    if mesh is None:
        return {}
    ids = np.vectorize(lambda d: d.id)(np.asarray(mesh.devices))
    names = list(mesh.axis_names)
    axes = list(range(ids.ndim))
    out: Dict[str, frozenset] = {}
    real = [i for i in axes if ids.shape[i] > 1]
    for r in range(1, len(real) + 1):
        for combo in itertools.combinations(real, r):
            others = [i for i in axes if i not in combo]
            size = 1
            for i in combo:
                size *= ids.shape[i]
            g = ids.transpose(others + list(combo)).reshape(-1, size)
            label = "+".join(names[i] for i in combo)
            out[label] = frozenset(frozenset(int(x) for x in row)
                                   for row in g)
    return out


def _parse_groups(line: str):
    """Replica groups on an HLO line -> frozenset of frozensets, or
    None when the line carries none (flat/default grouping)."""
    m = _GROUPS_RE.search(line)
    if m:
        groups = re.findall(r"\{([\d, ]*)\}", m.group(1))
        return frozenset(
            frozenset(int(x) for x in g.replace(" ", "").split(",") if x)
            for g in groups)
    m = _IOTA_GROUPS_RE.search(line)
    if m:
        import numpy as np
        n_groups, g_size = int(m.group(1)), int(m.group(2))
        dims = [int(d) for d in m.group(3).split(",")]
        ids = np.arange(int(np.prod(dims))).reshape(dims)
        if m.group(4):
            ids = ids.transpose([int(p) for p in m.group(4).split(",")])
        flat = ids.reshape(n_groups, g_size)
        return frozenset(frozenset(int(x) for x in row) for row in flat)
    return None


def _axis_of_pairs(line: str, mesh) -> Optional[str]:
    """Label a collective-permute by the mesh axis its source→target
    pairs move along (every pair differs in exactly one coordinate)."""
    import numpy as np
    m = _PAIRS_RE.search(line)
    if m is None or mesh is None:
        return None
    pairs = [tuple(int(x) for x in p.split(","))
             for p in re.findall(r"\{(\d+,\d+)\}", m.group(0))]
    if not pairs:
        return None
    ids = np.vectorize(lambda d: d.id)(np.asarray(mesh.devices))
    coord = {int(ids[idx]): idx for idx in np.ndindex(ids.shape)}
    names = list(mesh.axis_names)
    moved = set()
    for s, t in pairs:
        cs, ct = coord.get(s), coord.get(t)
        if cs is None or ct is None:
            return None
        diff = [i for i in range(len(cs)) if cs[i] != ct[i]]
        if len(diff) != 1:
            return None
        moved.add(diff[0])
    if len(moved) == 1:
        return names[moved.pop()]
    return None


def collect_manifest(hlo_text: str, mesh) -> Dict[Tuple[str, str],
                                                  List[int]]:
    """{(op-kind, axis-label): [site count, total result bytes]} from
    optimized HLO text. Sites are static program text — an op inside a
    while/scan body counts once. ``-done`` halves of async pairs are
    not separate sites (the regex matches only the ``-start``/sync
    spelling, which carries the shape)."""
    axis_groups = _mesh_axis_groups(mesh)
    n_dev = 0
    if mesh is not None:
        for _ax, sz in dict(mesh.shape).items():
            n_dev = (n_dev or 1) * sz
    manifest: Dict[Tuple[str, str], List[int]] = {}
    for line in hlo_text.splitlines():
        if "-done(" in line:
            continue
        m = _OP_RE.search(line)
        if m is None:
            continue
        shape_txt, kind = m.group(1), m.group(2)
        is_start = m.group(3) is not None
        if kind == "collective-permute":
            axis = _axis_of_pairs(line, mesh) or "other"
        else:
            groups = _parse_groups(line)
            axis = "other"
            if groups is None:
                # no/default grouping = one group of the whole mesh
                full = [lbl for lbl, g in axis_groups.items()
                        if len(g) == 1 and len(next(iter(g))) == n_dev]
                axis = full[0] if full else "all"
            else:
                for label, g in axis_groups.items():
                    if groups == g:
                        axis = label
                        break
        cell = manifest.setdefault((kind, axis), [0, 0])
        cell[0] += 1
        cell[1] += _shape_bytes(shape_txt, async_start=is_start)
    return manifest


def format_manifest(manifest: Dict[Tuple[str, str], List[int]]) -> str:
    if not manifest:
        return "no collectives"
    return ", ".join(
        f"{kind}x{n} ({axis}, {nbytes}B)"
        for (kind, axis), (n, nbytes) in sorted(manifest.items()))


def check_budget(program: str, manifest: Dict[Tuple[str, str], List[int]],
                 entries: List[BudgetEntry], anchor: str,
                 budget_rel: str) -> Tuple[List[Finding], List[int]]:
    """Compare one program's manifest against its budget entries.
    Returns (findings, indices of entries consumed)."""
    findings: List[Finding] = []
    used: List[int] = []
    by_key = {}
    for i, e in enumerate(entries):
        if e.program == program:
            by_key[(e.op, e.axis)] = (i, e)
    for (kind, axis), (n, nbytes) in sorted(manifest.items()):
        hit = by_key.get((kind, axis))
        if hit is None:
            findings.append(Finding(
                "PT501", anchor, 1,
                f"{program}: UNBUDGETED collective {kind} over "
                f"{axis!r} (x{n}, {nbytes} bytes) — the program grew "
                f"communication; justify it by adding the entry to "
                f"{budget_rel} in the same change, or remove the "
                "collective"))
            continue
        i, e = hit
        used.append(i)
        if n > e.ops or nbytes > e.bytes:
            findings.append(Finding(
                "PT501", anchor, 1,
                f"{program}: collective {kind} over {axis!r} GREW past "
                f"its budget: {n} sites / {nbytes} bytes vs budgeted "
                f"{e.ops} / {e.bytes} — communication drift (the r07 "
                "incident class); fix the program or justify the new "
                f"budget in {budget_rel}"))
        elif n < e.ops or nbytes < e.bytes:
            findings.append(Finding(
                "PT501", budget_rel, 1,
                f"{program}: collective {kind} over {axis!r} SHRANK to "
                f"{n} sites / {nbytes} bytes vs budgeted {e.ops} / "
                f"{e.bytes} — tighten the budget entry (the budget "
                "only shrinks; lock the win in)"))
    return findings, used


# ================================================= jaxpr checks (503/504)
def _sub_jaxprs(eqn):
    out = []
    for v in eqn.params.values():
        for sub in (v if isinstance(v, (list, tuple)) else [v]):
            if hasattr(sub, "jaxpr") or hasattr(sub, "eqns"):
                out.append(sub)
    return out


def _is_literal(v) -> bool:
    return hasattr(v, "val")


def _shardmap_in_sharded(eqn) -> List[bool]:
    """Per-operand: does the shard_map view this operand as split over
    a mesh axis? (in_names dicts on jax<=0.4; in_specs on newer.)"""
    names = eqn.params.get("in_names")
    if names is not None:
        return [bool(n) for n in names]
    specs = eqn.params.get("in_specs")
    if specs is not None:
        return [any(s is not None for s in spec) for spec in specs]
    return [True] * len(eqn.invars)


def shardmap_pin_findings(closed, name: str, anchor: str) -> List[Finding]:
    """PT503: shard_map operands with a sharded in_spec whose value was
    built by a pack op (concatenate/pad) with no sharding_constraint in
    between. Without the pin, sharding propagation leaks the
    shard_map's per-device demand into the producing program — in r07
    that rewrote the whole backward ~2x slower (``optim/zero1.py``
    pins both fused buffers replicated for exactly this reason).
    Origins are tracked through pjit/scan sub-jaxprs; operands that are
    program inputs, constants, or pinned values are exempt."""
    findings: List[Finding] = []

    INVAR, CONST, PINNED = "invar", "const", "pinned"

    def resolve(v, origin):
        if _is_literal(v):
            return CONST
        return origin.get(v, INVAR)

    def scan(jaxpr, origin):
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            for sub in _sub_jaxprs(eqn):
                inner = getattr(sub, "jaxpr", sub)
                inner_origin = {cv: CONST
                                for cv in getattr(inner, "constvars", [])}
                n = min(len(eqn.invars), len(inner.invars))
                for i in range(1, n + 1):  # tail-aligned (consts prepend)
                    inner_origin[inner.invars[-i]] = resolve(
                        eqn.invars[-i], origin)
                scan(inner, inner_origin)
            if prim == "shard_map":
                sharded = _shardmap_in_sharded(eqn)
                for i, v in enumerate(eqn.invars):
                    if i < len(sharded) and not sharded[i]:
                        continue
                    cat = resolve(v, origin)
                    if cat in _PACK_OPS:
                        shape = getattr(getattr(v, "aval", None),
                                        "shape", "?")
                        findings.append(Finding(
                            "PT503", anchor, 1,
                            f"{name}: shard_map operand {i} (shape "
                            f"{shape}) enters a sharded in_spec "
                            f"straight from a {cat} pack with no "
                            "with_sharding_constraint pin — "
                            "propagation can rewrite the producing "
                            "backward (the r07 2x regression); pin the "
                            "packed buffer (optim/zero1.py:update)"))
            if prim == "sharding_constraint":
                cat = PINNED
            elif prim in _THROUGH_OPS and eqn.invars:
                cat = resolve(eqn.invars[0], origin)
            else:
                cat = prim
            for ov in eqn.outvars:
                origin[ov] = cat

    scan(closed.jaxpr, {cv: CONST for cv in closed.jaxpr.constvars})
    return findings


def reshard_findings(closed, name: str, anchor: str) -> List[Finding]:
    """PT504: a value pinned to one sharding and then re-pinned to a
    DIFFERENT one along the same (value-preserving) chain — each such
    transition is a real reshard copy in the compiled program."""
    findings: List[Finding] = []

    def spec_of(eqn) -> str:
        s = eqn.params.get("sharding")
        return str(getattr(s, "spec", s))

    def scan(jaxpr):
        pinned: Dict[Any, str] = {}  # var -> spec-string it carries
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            for sub in _sub_jaxprs(eqn):
                scan(getattr(sub, "jaxpr", sub))
            if prim == "sharding_constraint":
                v = eqn.invars[0]
                prev = None if _is_literal(v) else pinned.get(v)
                spec = spec_of(eqn)
                if prev is not None and prev != spec:
                    findings.append(Finding(
                        "PT504", anchor, 1,
                        f"{name}: value pinned {prev} is re-pinned "
                        f"{spec} in the same program — a reshard copy "
                        "per transition; pin once at the producer"))
                for ov in eqn.outvars:
                    pinned[ov] = spec
            elif prim in _THROUGH_OPS and eqn.invars:
                v = eqn.invars[0]
                if not _is_literal(v) and v in pinned:
                    for ov in eqn.outvars:
                        pinned[ov] = pinned[v]

    scan(closed.jaxpr)
    return findings


# ===================================================== placement (PT502)
def replication_findings(args, must_shard, name: str,
                         anchor: str) -> List[Finding]:
    """PT502: leaves selected by a program's must-shard contract that
    are big (> BIG_BYTES), placed fully replicated, yet have a mesh
    axis (size > 1) dividing one of their dims. ``must_shard`` is a
    list of (label, path-predicate) pairs over
    ``jax.tree_util.keystr`` paths of the program args."""
    import jax

    # the dividing-axis gate is THE shared decision
    # (parallel/layout.py:axis_divides): the same predicate
    # SpecLayout.slot_sharding uses for its replicated fallback, so
    # the audit and the placement can never disagree about when
    # replication is legitimate
    from paddle_tpu.parallel.layout import axis_divides
    findings: List[Finding] = []
    if not must_shard:
        return findings
    flat, _ = jax.tree_util.tree_flatten_with_path(args)
    for path, leaf in flat:
        pstr = jax.tree_util.keystr(path)
        sharding = getattr(leaf, "sharding", None)
        nbytes = getattr(leaf, "nbytes", 0)
        if sharding is None or nbytes <= BIG_BYTES:
            continue
        for label, pred in must_shard:
            if not pred(pstr):
                continue
            if sharding.is_fully_replicated:
                mesh = getattr(sharding, "mesh", None)
                axes = [f"{ax}({sz})"
                        for ax, sz in dict(getattr(mesh, "shape",
                                                   {})).items()
                        if any(axis_divides(int(d), int(sz))
                               for d in leaf.shape)]
                if not axes:
                    # no axis divides any dim: placement legitimately
                    # falls back to replicated (SpecLayout's
                    # non-divisible warning path) — not a violation
                    continue
                findings.append(Finding(
                    "PT502", anchor, 1,
                    f"{name}: {label} leaf {pstr} ({nbytes} bytes, "
                    f"shape {tuple(leaf.shape)}) is FULLY REPLICATED "
                    f"despite matching mesh axes {', '.join(axes)} — "
                    "every device pays its full bytes; restore the "
                    "sharding rule/placement this program's contract "
                    "promises"))
    return findings


# ====================================================== rule tables (505)
def check_rule_table(rules, names: Iterable[str], anchor: str,
                     where: str, line: int = 1) -> List[Finding]:
    """PT505 hygiene for one ``rule_for`` table against the parameter
    names it governs: dead keys, ``=``-exact misses, shadowed keys.
    Matching/precedence come from ``parallel/mesh.py`` itself
    (``key_matches``/``rule_key_for``), so the audit can never drift
    from the semantics ``rule_for`` actually applies."""
    from paddle_tpu.parallel.mesh import key_matches, rule_key_for
    findings: List[Finding] = []
    if not rules:
        return findings
    names = list(names)
    for pat in rules:
        matched = [n for n in names if key_matches(pat, n)]
        if not matched:
            kind = ("exact-match key matches no parameter"
                    if pat.startswith("=") else
                    "substring key matches no parameter")
            findings.append(Finding(
                "PT505", anchor, line,
                f"{where}: rule key {pat!r} is DEAD ({kind} of "
                f"{len(names)}) — delete it or fix the name it meant "
                "to target"))
            continue
        effective = [n for n in matched if rule_key_for(n, rules) == pat]
        if not effective:
            shadows = sorted({rule_key_for(n, rules) for n in matched})
            findings.append(Finding(
                "PT505", anchor, line,
                f"{where}: rule key {pat!r} is fully SHADOWED by "
                f"{shadows} — every name it matches resolves to "
                "another key under rule_for precedence (=-exact keys "
                "first, then table order); delete it or retarget it"))
    return findings


# ======================================================== traced programs
class ProgramSpec:
    """One traced parallel program: a jitted fn + committed-sharding
    args + its mesh and contracts (one build feeds BOTH pass 4 and
    pass 5 — the ``build_scoring_predictor`` precedent).

    Pass-5 (``mem_audit``) contract fields:

    - ``mem_roles`` — ``(role, argnum, path-predicate-or-None)``
      triples classifying input leaves into the manifest's role
      breakdown (``params`` / ``opt_slots`` / ``acts``); leaves no
      triple claims are unclassified scaffolding (rng keys, step
      counters).
    - ``mem_laws`` — ``(label, argnum, path-predicate, divisor,
      slack[, override_bytes])`` scaling laws (PT602): the selected
      leaves' per-device bytes must stay within
      ``base / divisor * slack`` where ``base`` is their global bytes,
      or the optional 6th element when given — quantization laws pass
      the f32-equivalent byte count so a silent regression to f32
      storage violates even though the program's own global bytes
      track it.
    - ``donated`` — the top-level argnums the program donates (PT603
      checks their aliasable leaves reach the compiled alias set).
    """

    def __init__(self, name: str, anchor: str, fn, args, mesh,
                 must_shard=(), rule_tables=(), mem_roles=(),
                 mem_laws=(), donated=()):
        self.name = name
        self.anchor = anchor
        self.fn = fn
        self.args = args
        self.mesh = mesh
        self.must_shard = list(must_shard)
        # (rules, names, where) triples for PT505
        self.rule_tables = list(rule_tables)
        self.mem_roles = list(mem_roles)
        self.mem_laws = list(mem_laws)
        self.donated = tuple(donated)


class CompiledProgram:
    """A ProgramSpec compiled ONCE on the virtual mesh; pass 4 reads
    the optimized HLO for collectives, pass 5 reads the same
    executable's memory analysis — one compile, two audits."""

    def __init__(self, spec: ProgramSpec, compiled, hlo: str):
        self.spec = spec
        self.compiled = compiled
        self.hlo = hlo


def compile_program(spec: ProgramSpec) -> CompiledProgram:
    import warnings

    import jax
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # unusable-donation warnings
        jitted = spec.fn if hasattr(spec.fn, "lower") else jax.jit(spec.fn)
        compiled = jitted.lower(*spec.args).compile()
        return CompiledProgram(spec, compiled, compiled.as_text())


def compile_programs(log=None) -> List["CompiledProgram"]:
    """Build + SPMD-compile every traced program (the expensive step,
    shared by passes 4 and 5)."""
    out = []
    for build in PROGRAM_BUILDERS:
        spec = build()
        if log:
            log(f"  compiling {spec.name}...")
        out.append(compile_program(spec))
    return out


def _classifier_trainer(mesh, width=16, hidden=32, classes=4,
                        optimizer=None, shard_rules=None, seed=7):
    import numpy as np

    from paddle_tpu.config import dsl
    from paddle_tpu.data import DataFeeder, dense_vector, integer_value
    from paddle_tpu.optim import Momentum
    from paddle_tpu.trainer import SGD
    dsl.reset()
    x = dsl.data(name="x", size=width)
    lab = dsl.data(name="label", size=classes)
    h = dsl.fc(input=x, size=hidden, act="relu", name="h")
    out = dsl.fc(input=h, size=classes, act="softmax", name="out")
    cost = dsl.classification_cost(input=out, label=lab)
    tr = SGD(cost=cost,
             update_equation=optimizer or Momentum(learning_rate=0.1,
                                                   momentum=0.9),
             mesh=mesh, shard_rules=shard_rules, seed=seed)
    feeder = DataFeeder({"x": dense_vector(width),
                         "label": integer_value(classes)})
    rng = np.random.RandomState(0)
    data = [(rng.randn(width).astype(np.float32), int(rng.randint(classes)))
            for _ in range(16)]
    return tr, feeder(data)


def _step_args(tr, feed):
    import jax

    from paddle_tpu.parallel import mesh as mesh_lib
    feed = mesh_lib.shard_batch(feed, tr.mesh)
    return (tr.params, tr.opt_state, feed, jax.random.PRNGKey(0), 0, None)


# the train-step arg layout (params, opt_state, feed, rng, step, state):
# the shared role classification every trainer-built program uses
_TRAIN_ROLES = (("params", 0, None),
                ("opt_slots", 1, lambda p: "'slots'" in p),
                ("acts", 2, None))
_TRAIN_DONATED = (0, 1)  # _build_train_step's donate_argnums


def build_dp_train() -> ProgramSpec:
    """Plain data-parallel SGD: batch P(data) over all 8 devices,
    params replicated — the gradient all-reduce is the whole story."""
    from paddle_tpu.parallel.mesh import create_mesh
    mesh = create_mesh(n_data=8)
    tr, feed = _classifier_trainer(mesh)
    return ProgramSpec("dp_train", "paddle_tpu/trainer/trainer.py",
                       tr._train_step, _step_args(tr, feed), mesh,
                       mem_roles=_TRAIN_ROLES, donated=_TRAIN_DONATED)


def build_zero1() -> ProgramSpec:
    """ZeRO-1: slots packed (N, chunk) P(data), pinned fused pack
    buffers, ONE all-gather back (optim/zero1.py). The _h.w0 fc is
    sized past BIG_BYTES so the slot contract has teeth."""
    from paddle_tpu.optim import Adam
    from paddle_tpu.parallel.mesh import create_mesh
    mesh = create_mesh(n_data=8)
    tr, feed = _classifier_trainer(mesh, width=128, hidden=136,
                                   optimizer=Adam(learning_rate=1e-3))
    tr.enable_zero1()
    planned = sorted(tr._zero1.plan)
    must = [(f"zero1 slot of {n!r}",
             (lambda p, n=n: "'slots'" in p and f"'{n}'" in p))
            for n in planned]

    def planned_slot(p, names=tuple(planned)):
        return "'slots'" in p and any(f"'{n}'" in p for n in names)

    laws = [("zero1 planned slots shard ~1/8 over data", 1,
             planned_slot, 8, 1.1)]
    return ProgramSpec("zero1", "paddle_tpu/optim/zero1.py",
                       tr._train_step, _step_args(tr, feed), mesh,
                       must_shard=must, mem_roles=_TRAIN_ROLES,
                       mem_laws=laws, donated=_TRAIN_DONATED)


def build_pipeline() -> ProgramSpec:
    """The GPipe schedule: 4 identical fc stages stage-stacked P(pipe)
    composed with a 2-way data axis; handoff collective-permutes + the
    last-stage psum, and the usual grad all-reduce over data."""
    import numpy as np

    from paddle_tpu.config import dsl
    from paddle_tpu.data import DataFeeder, dense_vector, integer_value
    from paddle_tpu.optim import Adam
    from paddle_tpu.parallel.mesh import create_mesh
    from paddle_tpu.trainer import SGD
    width, classes, S = 8, 3, 4
    dsl.reset()
    x = dsl.data(name="x", size=width)
    lab = dsl.data(name="label", size=classes)
    h = x
    for s in range(S):
        h = dsl.fc(input=h, size=width, act="tanh", name=f"blk{s}",
                   layer_attr={"device": s})
    out = dsl.fc(input=h, size=classes, act="softmax", name="out")
    cost = dsl.classification_cost(input=out, label=lab)
    mesh = create_mesh(n_data=2, n_pipe=S)
    tr = SGD(cost=cost, update_equation=Adam(learning_rate=3e-3),
             mesh=mesh, seed=7)
    if not tr.enable_pipeline():
        raise RuntimeError("pipeline audit program stood down "
                           "(enable_pipeline returned False)")
    feeder = DataFeeder({"x": dense_vector(width),
                         "label": integer_value(classes)})
    rng = np.random.RandomState(0)
    data = [(rng.randn(width).astype(np.float32), int(rng.randint(classes)))
            for _ in range(8)]
    feed = feeder(data)
    plan = tr._pipe
    stacked = sorted(plan.stacked_map)
    must = [(f"stage-stacked {k!r}", (lambda p, k=k: f"'{k}'" in p))
            for k in stacked]
    slot_names = set(tr.opt_state.get("slots", {}))
    tables = [(plan.shard_rules(),
               sorted(set(tr.params) | slot_names),
               "parallel/pipeline.py:PipelineTrainPlan.shard_rules")]
    if tr._shard_rules:
        tables.append((tr._shard_rules, sorted(set(tr.params) | slot_names),
                       "trainer shard_rules (pipeline program)"))

    def stacked_leaf(p, keys=tuple(stacked)):
        return any(f"'{k}'" in p for k in keys)

    laws = [("stage-stacked body params shard 1/4 over pipe", 0,
             stacked_leaf, S, 1.05),
            ("stage-stacked body slots shard 1/4 over pipe", 1,
             (lambda p: "'slots'" in p and stacked_leaf(p)), S, 1.05)]
    return ProgramSpec("pipeline", "paddle_tpu/parallel/pipeline.py",
                       tr._train_step, _step_args(tr, feed), mesh,
                       must_shard=must, rule_tables=tables,
                       mem_roles=_TRAIN_ROLES, mem_laws=laws,
                       donated=_TRAIN_DONATED)


def build_tp_embed() -> ProgramSpec:
    """Tensor parallelism: embedding rows sharded P(model) through a
    full train step (the SparseRowMatrix row-slice placement); the
    table is sized past BIG_BYTES so PT502 guards the rule."""
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.config import dsl
    from paddle_tpu.data import (DataFeeder, integer_value,
                                 integer_value_sequence)
    from paddle_tpu.optim import Momentum
    from paddle_tpu.parallel.mesh import create_mesh
    from paddle_tpu.trainer import SGD
    vocab, dim = 1056, 16  # 1056*16*4 = 67584 B > BIG_BYTES
    dsl.reset()
    words = dsl.data(name="w", size=vocab, is_sequence=True)
    lab = dsl.data(name="label", size=2)
    emb = dsl.embedding(input=words, size=dim, vocab_size=vocab,
                        name="emb")
    pooled = dsl.pooling(input=emb, pooling_type="max")
    out = dsl.fc(input=pooled, size=2, act="softmax", name="out")
    cost = dsl.classification_cost(input=out, label=lab)
    mesh = create_mesh(n_data=4, n_model=2)
    tr = SGD(cost=cost, update_equation=Momentum(learning_rate=0.1),
             mesh=mesh, shard_rules={"_emb.w0": P("model", None)},
             seed=7)
    feeder = DataFeeder({"w": integer_value_sequence(vocab),
                         "label": integer_value(2)}, pad_multiple=8)
    rng = np.random.RandomState(0)
    data = [(list(rng.randint(0, vocab, size=rng.randint(2, 8))),
             int(rng.randint(0, 2))) for _ in range(16)]
    feed = feeder(data)
    must = [("model-sharded table '_emb.w0'",
             lambda p: "'_emb.w0'" in p)]
    tables = [(tr._shard_rules, sorted(tr.params),
               "trainer shard_rules (tp_embed program)")]
    laws = [("model-sharded table '_emb.w0' shards 1/2 over model", 0,
             (lambda p: "'_emb.w0'" in p), 2, 1.05)]
    return ProgramSpec("tp_embed", "paddle_tpu/parallel/mesh.py",
                       tr._train_step, _step_args(tr, feed), mesh,
                       must_shard=must, rule_tables=tables,
                       mem_roles=_TRAIN_ROLES, mem_laws=laws,
                       donated=_TRAIN_DONATED)


def build_seq_ring() -> ProgramSpec:
    """Sequence parallelism: ring attention fwd+bwd over a 4-way seq
    axis — the KV ppermute ring (parallel/ring.py), backward included
    because training is what rides it."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddle_tpu.parallel.mesh import create_mesh
    from paddle_tpu.parallel.ring import make_ring_attention
    mesh = create_mesh(n_data=2, n_seq=4)
    attn = make_ring_attention(mesh, "seq", kind="ring", causal=True)

    def loss(q, k, v, mask):
        return jnp.sum(attn(q, k, v, mask) ** 2)

    fn = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))
    B, N, T, D = 2, 2, 8, 4
    spec = NamedSharding(mesh, P(None, None, "seq", None))
    mspec = NamedSharding(mesh, P(None, "seq"))
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 3)
    q, k, v = (jax.device_put(
        jax.random.normal(ks[i], (B, N, T, D), jnp.float32), spec)
        for i in range(3))
    mask = jax.device_put(jnp.ones((B, T), jnp.float32), mspec)
    return ProgramSpec("seq_ring", "paddle_tpu/parallel/ring.py",
                       fn, (q, k, v, mask), mesh,
                       mem_roles=[("acts", i, None) for i in range(4)])


def build_fsdp_train() -> ProgramSpec:
    """Full FSDP: parameters flat-packed 1/8 over the dedicated fsdp
    axis (``optim/zero1.py:FsdpUpdater``) with ONE all-gather per layer
    on use, gradients reduced back into the packed layout, and the
    shard-wise update keeping everything sharded (no trailing gather).
    Sized so the contracts have teeth: per-device param bytes exceed
    ``BIG_BYTES``, so PT604's largest-temp threshold tracks the REAL
    param bytes — a refactor that gathers the whole packed set into one
    buffer (~8× the per-device params) fails PT604, and the ~1/8
    per-device scaling is a PT602 law, not an aspiration (ROADMAP
    item 1's acceptance criterion)."""
    import numpy as np

    from paddle_tpu.config import dsl
    from paddle_tpu.data import DataFeeder, dense_vector, integer_value
    from paddle_tpu.optim import Adam
    from paddle_tpu.parallel.mesh import create_mesh
    from paddle_tpu.trainer import SGD
    width, depth, classes = 136, 8, 4
    dsl.reset()
    x = dsl.data(name="x", size=16)
    lab = dsl.data(name="label", size=classes)
    h = dsl.fc(input=x, size=width, act="relu", name="fin")
    for i in range(depth):
        h = dsl.fc(input=h, size=width, act="relu", name=f"fh{i}")
    out = dsl.fc(input=h, size=classes, act="softmax", name="fout")
    cost = dsl.classification_cost(input=out, label=lab)
    mesh = create_mesh(n_fsdp=8)
    tr = SGD(cost=cost, update_equation=Adam(learning_rate=1e-3),
             mesh=mesh, seed=7)
    if not tr.enable_fsdp():
        raise RuntimeError("fsdp audit program stood down "
                           "(enable_fsdp returned False)")
    feeder = DataFeeder({"x": dense_vector(16),
                         "label": integer_value(classes)})
    rng = np.random.RandomState(0)
    data = [(rng.randn(16).astype(np.float32), int(rng.randint(classes)))
            for _ in range(16)]
    feed = feeder(data)
    planned = tuple(sorted(tr._fsdp.plan))

    # mem_laws preds see PER-ARG paths (the argnum filters the role);
    # must_shard preds see the whole-args-tuple paths ([0] prefix)
    def planned_leaf(p, names=planned):
        return any(f"'{n}'" in p for n in names)

    def planned_slot(p, names=planned):
        return "'slots'" in p and planned_leaf(p, names)

    must = [(f"fsdp-packed param {n!r}",
             (lambda p, n=n: p.startswith("[0]") and f"'{n}'" in p))
            for n in planned]
    laws = [("fsdp params shard ~1/8 over fsdp", 0, planned_leaf, 8,
             1.1),
            ("fsdp slots shard ~1/8 over fsdp", 1, planned_slot, 8,
             1.1)]
    return ProgramSpec("fsdp_train", "paddle_tpu/optim/zero1.py",
                       tr._train_step, _step_args(tr, feed), mesh,
                       must_shard=must, mem_roles=_TRAIN_ROLES,
                       mem_laws=laws, donated=_TRAIN_DONATED)


def build_fsdp_pipe() -> ProgramSpec:
    """The composed plane: GPipe stage-stacked body over ``pipe`` WITH
    the unstaged head flat-packed over ``fsdp`` — the two plans carved
    from ONE SpecLayout rule table (the stacked keys' ``P(pipe)`` pins
    exclude them from the fsdp plan; ``parallel/layout.py``). Both
    scaling laws hold simultaneously: body 1/S over pipe, head ~1/2
    over fsdp."""
    import numpy as np

    from paddle_tpu.config import dsl
    from paddle_tpu.data import DataFeeder, dense_vector, integer_value
    from paddle_tpu.optim import Adam
    from paddle_tpu.parallel.mesh import create_mesh
    from paddle_tpu.trainer import SGD
    width, classes, S = 8, 3, 4
    dsl.reset()
    x = dsl.data(name="x", size=width)
    lab = dsl.data(name="label", size=classes)
    h = x
    for s in range(S):
        h = dsl.fc(input=h, size=width, act="tanh", name=f"fpb{s}",
                   layer_attr={"device": s})
    out = dsl.fc(input=h, size=classes, act="softmax", name="fpout")
    cost = dsl.classification_cost(input=out, label=lab)
    mesh = create_mesh(n_data=1, n_fsdp=2, n_pipe=S)
    tr = SGD(cost=cost, update_equation=Adam(learning_rate=3e-3),
             mesh=mesh, seed=7)
    if not tr.enable_pipeline():
        raise RuntimeError("fsdp_pipe audit program stood down "
                           "(enable_pipeline returned False)")
    if not tr.enable_fsdp():
        raise RuntimeError("fsdp_pipe audit program stood down "
                           "(enable_fsdp returned False)")
    feeder = DataFeeder({"x": dense_vector(width),
                         "label": integer_value(classes)})
    rng = np.random.RandomState(0)
    data = [(rng.randn(width).astype(np.float32), int(rng.randint(classes)))
            for _ in range(8)]
    feed = feeder(data)
    plan = tr._pipe
    stacked = tuple(sorted(plan.stacked_map))
    planned = tuple(sorted(tr._fsdp.plan))
    assert not set(stacked) & set(planned), (
        "layout leak: stage-stacked keys entered the fsdp plan")
    slot_names = set(tr.opt_state.get("slots", {}))
    tables = [(plan.shard_rules(),
               sorted(set(tr.params) | slot_names),
               "parallel/pipeline.py:PipelineTrainPlan.shard_rules "
               "(fsdp_pipe)")]

    def stacked_leaf(p, keys=stacked):
        return any(f"'{k}'" in p for k in keys)

    def planned_leaf(p, names=planned):
        return any(f"'{n}'" in p for n in names)

    def planned_slot(p, names=planned):
        return "'slots'" in p and planned_leaf(p, names)

    must = [(f"stage-stacked {k!r}", (lambda p, k=k: f"'{k}'" in p))
            for k in stacked] + \
           [(f"fsdp-packed head param {n!r}",
             (lambda p, n=n: p.startswith("[0]") and f"'{n}'" in p))
            for n in planned]
    laws = [("stage-stacked body params shard 1/4 over pipe", 0,
             stacked_leaf, S, 1.05),
            ("stage-stacked body slots shard 1/4 over pipe", 1,
             (lambda p: "'slots'" in p and stacked_leaf(p)), S, 1.05),
            ("fsdp head params shard ~1/2 over fsdp", 0, planned_leaf,
             2, 1.1),
            ("fsdp head slots shard ~1/2 over fsdp", 1, planned_slot,
             2, 1.1)]
    return ProgramSpec("fsdp_pipe", "paddle_tpu/parallel/layout.py",
                       tr._train_step, _step_args(tr, feed), mesh,
                       must_shard=must, rule_tables=tables,
                       mem_roles=_TRAIN_ROLES, mem_laws=laws,
                       donated=_TRAIN_DONATED)


def build_serving_warm() -> ProgramSpec:
    """The serving warm path (_infer of a masked scorer, donate=True,
    exactly as warmup compiles it). Its budget is pinned EMPTY: the
    single-program serving step must never grow a collective."""
    from paddle_tpu.analysis.jaxpr_audit import build_scoring_predictor
    pred, args = build_scoring_predictor()
    import jax
    fn = jax.jit(pred._infer, donate_argnums=(1,))
    return ProgramSpec("serving_warm", "paddle_tpu/serving/predictor.py",
                       fn, args, None,
                       mem_roles=(("params", 0, None), ("acts", 1, None)),
                       donated=(1,))


def build_serving_quant() -> ProgramSpec:
    """The int8-quantized serving warm path: the SAME scorer as
    serving_warm with ``--quantize=int8`` storage (int8 leaves +
    traced scale siblings, dequant fused in-trace). Collective budget
    pinned EMPTY like serving_warm; its PT601 pin IS the quantization
    footprint win, and the PT602 law compares the params argument
    against the fp32 twin's byte count (the 6th law element) — a
    quantized program whose weights silently re-materialize as f32
    residents violates even though its own global bytes grew in
    lockstep."""
    from paddle_tpu.analysis.jaxpr_audit import build_quant_predictor
    pred, args, f32_bytes = build_quant_predictor()
    import jax
    fn = jax.jit(pred._infer, donate_argnums=(1,))
    laws = [("int8 params resident ~1/4 of the fp32 twin", 0, None,
             3, 1.35, f32_bytes)]
    return ProgramSpec("serving_quant",
                       "paddle_tpu/serving/predictor.py",
                       fn, args, None,
                       mem_roles=(("params", 0, None), ("acts", 1, None)),
                       mem_laws=laws, donated=(1,))


PROGRAM_BUILDERS: List[Callable[[], ProgramSpec]] = [
    build_dp_train, build_zero1, build_pipeline, build_tp_embed,
    build_seq_ring, build_fsdp_train, build_fsdp_pipe,
    build_serving_warm, build_serving_quant,
]

PROGRAM_NAMES = ("dp_train", "zero1", "pipeline", "tp_embed",
                 "seq_ring", "fsdp_train", "fsdp_pipe", "serving_warm",
                 "serving_quant")


# ============================================================== the pass
def audit_program(cp: CompiledProgram, entries: List[BudgetEntry],
                  budget_rel: str, log=None
                  ) -> Tuple[List[Finding], List[int]]:
    """All pass-4 checks for one compiled program."""
    import jax
    spec = cp.spec
    findings: List[Finding] = []
    manifest = collect_manifest(cp.hlo, spec.mesh)
    bfind, used = check_budget(spec.name, manifest, entries,
                               spec.anchor, budget_rel)
    findings.extend(bfind)
    closed = jax.make_jaxpr(spec.fn)(*spec.args)
    findings.extend(shardmap_pin_findings(closed, spec.name, spec.anchor))
    findings.extend(reshard_findings(closed, spec.name, spec.anchor))
    findings.extend(replication_findings(spec.args, spec.must_shard,
                                         spec.name, spec.anchor))
    for rules, names, where in spec.rule_tables:
        findings.extend(check_rule_table(rules, names, spec.anchor,
                                         where))
    if log:
        log(f"  {spec.name}: {format_manifest(manifest)}")
    return findings, used


def run_pass4(root: Optional[str] = None, log=print,
              budget_path: Optional[str] = None,
              programs: Optional[List[CompiledProgram]] = None
              ) -> List[Finding]:
    """Trace, partition, and audit all parallel programs; enforce the
    committed collective budget including its stale-entry policy.
    ``programs`` lets the CLI compile once and feed both pass 4 and
    pass 5 (``mem_audit.run_pass5``) from the same executables."""
    budget_path = budget_path or default_budget_path()
    budget_rel = os.path.relpath(
        budget_path, root or os.getcwd()).replace(os.sep, "/")
    entries = load_budget(budget_path)
    findings: List[Finding] = []
    used: set = set()
    for cp in programs if programs is not None else compile_programs():
        fs, u = audit_program(cp, entries, budget_rel, log=log)
        findings.extend(fs)
        used.update(u)
    findings.extend(stale_budget_findings(entries, used, budget_rel))
    return findings


def stale_budget_findings(entries: List[BudgetEntry], used,
                          budget_rel: str) -> List[Finding]:
    """Budget entries no traced program consumed: same policy as stale
    baseline entries — they must be deleted, or they sit pinned to a
    collective that no longer exists and hide the next regression."""
    findings: List[Finding] = []
    for i, e in enumerate(entries):
        if i in used:
            continue
        if e.program not in PROGRAM_NAMES:
            why = f"names unknown program {e.program!r}"
        else:
            why = (f"matches no collective the traced {e.program} "
                   "program emits")
        findings.append(Finding(
            "PT501", budget_rel, 1,
            f"STALE budget entry (program={e.program} op={e.op} "
            f"axis={e.axis!r}) {why} — delete it (the budget only "
            "shrinks)"))
    return findings
