"""Pass 5 — per-device memory-footprint audit of the compiled
parallel programs.

Pass 4 pins what the programs *communicate*; this pass pins what they
*hold*. ROADMAP item 1's acceptance criterion — "param bytes/device
scale ~1/N" under FSDP — and item 4's memory-aware admission both need
byte budgets that are artifacts, not hopes (the 2017 reference's whole
v1 memory story is per-parameter device placement, ``paddle/memory``;
the TensorFlow cluster/placement design in PAPERS.md argues the same).
Nothing before this pass caught a refactor that silently replicates a
buffer, doubles a temp, or un-donates an aliased leaf.

The pass reuses pass 4's ``.lower().compile()`` of the same nine real
programs on the 8-device virtual mesh (``shard_audit.compile_programs``
— ONE compile feeds both passes) and reads each executable's
``memory_analysis()``: per-device argument / output / temp / alias
bytes, plus a per-role breakdown (params / opt slots / activations)
computed from the compiled input shardings the way
``utils/profiler.memory_stats`` computes it from live arrays.

Checks:

- **PT601 memory budget**: the manifest must match
  ``analysis/mem_budget.toml`` exactly, with the proven ratchet
  semantics — growth is drift, unpinned shrinkage fails so wins lock
  in, stale entries are findings, and (unlike the comm budget, where
  zero is spelled by absence) EVERY traced program must be pinned:
  memory is never zero, and serving_warm's resident working set is the
  item-4 admission number.
- **PT602 sharding-efficiency law**: per-role bytes/device must match
  the program's declared scaling (zero1 slots ~1/N over data, pipeline
  stacked body ~1/S over pipe, the TP table ~1/M over model). The FSDP
  PR's "param bytes ~1/N" lands against this rule.
- **PT603 donation honesty**: every donated leaf the jaxpr audit
  (PT202) records as aliasable must reach the compiled executable's
  ``input_output_alias``/``buffer_donor`` set, and aliasing must
  actually shrink the footprint (``alias_size_in_bytes > 0``) — not
  just carry the StableHLO annotation.
- **PT604 temp blow-up**: no single temp buffer may exceed the
  program's total per-device param bytes (floored at ``BIG_BYTES`` so
  tiny audit models don't false-positive) — the
  full-gather-materialization smell FSDP must not regress into.
- **PT605 static-vs-runtime agreement**: the manifest's per-role
  bytes/device must reconcile exactly with
  ``utils/profiler.memory_stats`` on the same params / opt_state /
  activations — one invariant enforced from both sides (the
  ``assert_mask_f32`` pattern).

Heavy imports (jax, the program builders) stay inside functions:
pass 1/3 and ``--fast`` must not pay them.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Tuple

from paddle_tpu.analysis.findings import Finding
from paddle_tpu.analysis.shard_audit import (BIG_BYTES, CompiledProgram,
                                             PROGRAM_NAMES,
                                             compile_programs)

# the pinned manifest fields, in budget/report order; all per-device
MANIFEST_FIELDS = ("arg_bytes", "out_bytes", "temp_bytes", "alias_bytes",
                   "resident_bytes", "param_bytes", "slot_bytes",
                   "act_bytes")

# compiled-HLO opcodes whose result is not its own device allocation:
# parameters are argument bytes, tuples/GTEs/bitcasts alias existing
# buffers, while/conditional/call results alias their body buffers
_NON_ALLOC_OPS = {"parameter", "tuple", "get-tuple-element", "bitcast",
                  "while", "conditional", "call"}

_HLO_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(\([^=]*?\)|\S+)\s+([a-z][\w\-]*)\(")
_ALIAS_ENTRY_RE = re.compile(r"\}:\s*\((\d+),")
_DONOR_ENTRY_RE = re.compile(r"\((\d+),")

# the ZeRO-1 fused all-gather result is the packed param set plus its
# chunk padding (optim/zero1.py rounds each leaf up to a multiple of
# the shard count) — a legitimate buffer a hair over param bytes; the
# smell PT604 hunts is a MULTIPLE of the param set, so a few percent
# of pack slack never masks it
PACK_SLACK = 1.05


# ============================================================ mem budget
def default_budget_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "mem_budget.toml")


class MemBudgetEntry:
    __slots__ = ("program",) + MANIFEST_FIELDS

    def __init__(self):
        self.program = ""
        for f in MANIFEST_FIELDS:
            setattr(self, f, 0)


def load_mem_budget(path: Optional[str] = None) -> List[MemBudgetEntry]:
    """Parse ``mem_budget.toml`` (the shared TOML-subset table parser
    from baseline.py). Unlike the comm budget, zero is a legal pinned
    value for most fields (seq_ring donates nothing, so its alias
    bytes ARE 0) — only ``arg_bytes`` must be >= 1 (a program with no
    argument bytes was not compiled from real inputs), and
    ``resident_bytes`` must reconcile with its components so a hand
    edit cannot silently break the admission number."""
    from paddle_tpu.analysis.baseline import parse_toml_tables
    path = path or default_budget_path()
    if not os.path.exists(path):
        return []
    entries = parse_toml_tables(
        path, "mem budget", "[[memory]]", MemBudgetEntry,
        int_keys=MANIFEST_FIELDS, str_keys=("program",))
    seen: Dict[str, int] = {}
    for e in entries:
        if not e.program:
            raise ValueError(
                f"mem budget {path}: every [[memory]] needs program=")
        if e.arg_bytes < 1:
            raise ValueError(
                f"mem budget {path}: entry for {e.program} needs "
                "arg_bytes >= 1 (every compiled program has argument "
                "bytes; a zero here means the pin was never generated)")
        for f in MANIFEST_FIELDS:
            if getattr(e, f) < 0:
                raise ValueError(
                    f"mem budget {path}: entry for {e.program} has "
                    f"negative {f}")
        derived = (e.arg_bytes + e.out_bytes + e.temp_bytes
                   - e.alias_bytes)
        if e.resident_bytes != derived:
            raise ValueError(
                f"mem budget {path}: entry for {e.program} pins "
                f"resident_bytes={e.resident_bytes} but arg+out+temp"
                f"-alias = {derived} — the admission number must "
                "reconcile with its components")
        if e.program in seen:
            raise ValueError(
                f"mem budget {path}: duplicate entry for "
                f"{e.program} — merge-conflict leftovers would "
                "silently resolve to the last one")
        seen[e.program] = 1
    return entries


# ===================================================== manifest extraction
def _leaf_rows(cp: CompiledProgram) -> List[Tuple[Optional[int], int,
                                                  str, object, object]]:
    """``(flat_hlo_param_idx, argnum, path, leaf, compiled_sharding)``
    per input leaf. Shardings come from the COMPILED executable — what
    the partitioner actually placed — not from the arg arrays; PT605
    closes the loop against the array side. A leaf jit PRUNED from the
    executable (an unused rng key / step counter: its sharding subtree
    is ``None``) gets ``(None, ..., sharding=None)`` — it occupies no
    device bytes and no HLO parameter slot."""
    import jax.tree_util as jtu
    in_shardings = cp.compiled.input_shardings[0]
    rows: List[Tuple[Optional[int], int, str, object, object]] = []
    flat_idx = 0
    for argnum, arg in enumerate(cp.spec.args):
        flat, _ = jtu.tree_flatten_with_path(arg)
        stree = (in_shardings[argnum] if argnum < len(in_shardings)
                 else None)
        sflat, _ = jtu.tree_flatten_with_path(stree)
        by_path = {jtu.keystr(p): s for p, s in sflat}
        leaf_paths = {jtu.keystr(p) for p, _l in flat}
        extra = sorted(set(by_path) - leaf_paths)[:3]
        if extra:
            raise RuntimeError(
                f"{cp.spec.name}: arg {argnum} compiled shardings "
                f"carry paths absent from the arg pytree ({extra}) — "
                "the audit's leaf/parameter alignment broke")
        for path, leaf in flat:
            key = jtu.keystr(path)
            sharding = by_path.get(key)
            rows.append((flat_idx if sharding is not None else None,
                         argnum, key, leaf, sharding))
            if sharding is not None:
                flat_idx += 1
    return rows


def _leaf_device_bytes(leaf, sharding) -> int:
    """Bytes ONE device holds for a leaf under the compiled sharding
    (the ``utils/profiler._leaf_device_bytes`` accounting, applied to
    the partitioner's own placement)."""
    shape = tuple(getattr(leaf, "shape", ()))
    dtype = getattr(leaf, "dtype", None)
    if dtype is None:
        return 0
    if sharding is not None and hasattr(sharding, "shard_shape"):
        try:
            shape = sharding.shard_shape(shape)
        except (TypeError, ValueError):
            pass
    n = 1
    for d in shape:
        n *= int(d)
    return n * dtype.itemsize


def role_bytes(cp: CompiledProgram) -> Dict[str, int]:
    """Per-role per-device bytes from the compiled input shardings.
    Roles a spec does not declare report 0; leaves no role claims
    (rng keys, step counters) are deliberately unclassified."""
    out = {"param_bytes": 0, "slot_bytes": 0, "act_bytes": 0}
    key = {"params": "param_bytes", "opt_slots": "slot_bytes",
           "acts": "act_bytes"}
    for flat_idx, argnum, path, leaf, sharding in _leaf_rows(cp):
        if flat_idx is None:
            continue  # pruned from the executable: no device bytes
        for role, rnum, pred in cp.spec.mem_roles:
            if rnum == argnum and (pred is None or pred(path)):
                out[key[role]] += _leaf_device_bytes(leaf, sharding)
                break
    return out


def memory_manifest(cp: CompiledProgram) -> Dict[str, int]:
    """The per-device memory manifest of one compiled program:
    ``memory_analysis()`` totals + the role breakdown.
    ``resident_bytes`` — arguments + outputs + temps − aliased — is
    the resident working set a device needs to admit the program (the
    ROADMAP item-4 admission number)."""
    ma = cp.compiled.memory_analysis()
    m = {"arg_bytes": int(ma.argument_size_in_bytes),
         "out_bytes": int(ma.output_size_in_bytes),
         "temp_bytes": int(ma.temp_size_in_bytes),
         "alias_bytes": int(ma.alias_size_in_bytes)}
    m["resident_bytes"] = (m["arg_bytes"] + m["out_bytes"]
                           + m["temp_bytes"] - m["alias_bytes"])
    m.update(role_bytes(cp))
    return m


def format_mem_manifest(m: Dict[str, int]) -> str:
    return (f"resident {m['resident_bytes']}B (arg {m['arg_bytes']} + "
            f"out {m['out_bytes']} + temp {m['temp_bytes']} - alias "
            f"{m['alias_bytes']}); roles param {m['param_bytes']} / "
            f"slot {m['slot_bytes']} / act {m['act_bytes']}")


# ================================================================ PT601
def check_mem_budget(program: str, manifest: Dict[str, int],
                     entries: List[MemBudgetEntry], anchor: str,
                     budget_rel: str) -> Tuple[List[Finding], List[int]]:
    """Exact two-sided comparison of one program's manifest against its
    pinned entry. Returns (findings, indices of entries consumed)."""
    findings: List[Finding] = []
    used: List[int] = []
    hit = None
    for i, e in enumerate(entries):
        if e.program == program:
            hit = (i, e)
            break
    if hit is None:
        findings.append(Finding(
            "PT601", budget_rel, 1,
            f"{program}: UNPINNED traced program — every program's "
            f"memory manifest must be committed ({format_mem_manifest(manifest)}); "
            f"add its [[memory]] entry to {budget_rel}"))
        return findings, used
    i, e = hit
    used.append(i)
    for f in MANIFEST_FIELDS:
        cur, pin = manifest[f], getattr(e, f)
        if cur > pin:
            findings.append(Finding(
                "PT601", anchor, 1,
                f"{program}: {f} GREW past its budget: {cur} vs "
                f"pinned {pin} — per-device footprint drift (the "
                "silently-replicated-buffer class); fix the program "
                f"or justify the new pin in {budget_rel}"))
        elif cur < pin:
            findings.append(Finding(
                "PT601", budget_rel, 1,
                f"{program}: {f} SHRANK to {cur} vs pinned {pin} — "
                "tighten the budget entry (the budget only shrinks; "
                "lock the win in)"))
    return findings, used


def stale_mem_budget_findings(entries: List[MemBudgetEntry], used,
                              budget_rel: str) -> List[Finding]:
    findings: List[Finding] = []
    for i, e in enumerate(entries):
        if i in used:
            continue
        why = ("names unknown program " + repr(e.program)
               if e.program not in PROGRAM_NAMES
               else "was not consumed by the traced programs")
        findings.append(Finding(
            "PT601", budget_rel, 1,
            f"STALE mem budget entry (program={e.program}) {why} — "
            "delete it (the budget only shrinks)"))
    return findings


# ================================================================ PT602
def scaling_findings(cp: CompiledProgram) -> List[Finding]:
    """Each declared law: the selected leaves' per-device bytes (under
    the COMPILED shardings) must stay within base/divisor * slack,
    where base is the matched leaves' global bytes — or the law's
    explicit override (the optional 6th element): quantization laws
    pass the f32-EQUIVALENT byte count there, so an int8 program whose
    leaves silently regress to f32 storage blows the law even though
    "its own" global bytes grew in lockstep. A law whose selector
    matches nothing is itself a finding — a renamed key must not
    silently vacate the contract."""
    findings: List[Finding] = []
    if not cp.spec.mem_laws:
        return findings
    rows = _leaf_rows(cp)
    for law in cp.spec.mem_laws:
        label, argnum, pred, divisor, slack = law[:5]
        override_b = law[5] if len(law) > 5 else None
        global_b = 0
        device_b = 0
        matched = 0
        for flat_idx, anum, path, leaf, sharding in rows:
            if anum != argnum or (pred is not None and not pred(path)):
                continue
            matched += 1
            global_b += _leaf_device_bytes(leaf, None)
            if flat_idx is not None:  # pruned leaves hold no bytes
                device_b += _leaf_device_bytes(leaf, sharding)
        if not matched:
            findings.append(Finding(
                "PT602", cp.spec.anchor, 1,
                f"{cp.spec.name}: scaling law {label!r} selects no "
                "input leaf — the law's selector no longer matches "
                "the program (audit contract broke; fix the selector "
                "or the program)"))
            continue
        base_b = override_b if override_b is not None else global_b
        allowed = int(base_b / divisor * slack)
        if device_b > allowed:
            base_src = ("override" if override_b is not None
                        else "global")
            findings.append(Finding(
                "PT602", cp.spec.anchor, 1,
                f"{cp.spec.name}: scaling law {label!r} VIOLATED — "
                f"{matched} leaves hold {device_b} bytes/device vs "
                f"allowed {allowed} ({base_b} {base_src} / {divisor}, "
                f"slack {slack}) — the program's promised per-device "
                "scaling regressed"))
    return findings


# ================================================================ PT603
def _brace_block(text: str, key: str) -> str:
    """The brace-balanced payload of ``key={...}`` in HLO header text
    (the entries themselves contain nested ``{0}: (0, {}, ...)``
    braces, which a regex alternation mis-scans)."""
    i = text.find(key + "={")
    if i < 0:
        return ""
    j = i + len(key) + 2
    depth = 1
    start = j
    while j < len(text) and depth:
        if text[j] == "{":
            depth += 1
        elif text[j] == "}":
            depth -= 1
        j += 1
    return text[start:j - 1]


def _compiled_alias_params(hlo: str) -> set:
    """Flat parameter numbers the compiled module records as aliased
    (``input_output_alias``) or donated (``buffer_donor``)."""
    params: set = set()
    params.update(int(p) for p in _ALIAS_ENTRY_RE.findall(
        _brace_block(hlo, "input_output_alias")))
    params.update(int(p) for p in _DONOR_ENTRY_RE.findall(
        _brace_block(hlo, "buffer_donor")))
    return params


def donation_findings(cp: CompiledProgram,
                      manifest: Dict[str, int]) -> List[Finding]:
    """Donation honesty: every donated leaf whose (shape, dtype)
    matches an output leaf — the same aliasing precondition PT202
    checks at the StableHLO level — must appear in the COMPILED
    module's ``input_output_alias``/``buffer_donor`` header, and when
    any such leaf exists the executable's alias bytes must be > 0 (the
    annotation must shrink the argument+temp footprint, not just ride
    along)."""
    import jax
    spec = cp.spec
    findings: List[Finding] = []
    if not spec.donated:
        return findings
    rows = _leaf_rows(cp)
    out_pool: Dict[Tuple[Tuple[int, ...], str], int] = {}
    for leaf in jax.tree_util.tree_leaves(
            jax.eval_shape(spec.fn, *spec.args)):
        k = (tuple(leaf.shape), str(leaf.dtype))
        out_pool[k] = out_pool.get(k, 0) + 1
    compiled_set = _compiled_alias_params(cp.hlo)
    aliasable = 0
    for flat_idx, argnum, path, leaf, _s in rows:
        if argnum not in spec.donated or flat_idx is None:
            continue
        k = (tuple(getattr(leaf, "shape", ())),
             str(getattr(leaf, "dtype", "")))
        if out_pool.get(k, 0) <= 0:
            continue
        out_pool[k] -= 1
        aliasable += 1
        if flat_idx not in compiled_set:
            findings.append(Finding(
                "PT603", spec.anchor, 1,
                f"{spec.name}: donated leaf arg{argnum}{path} "
                f"(shape {k[0]}, {k[1]}) is aliasable but missing "
                "from the compiled module's input_output_alias/"
                "buffer_donor set — the donation annotation did not "
                "survive compilation; the device will hold input AND "
                "output copies"))
    if aliasable and manifest["alias_bytes"] == 0:
        findings.append(Finding(
            "PT603", spec.anchor, 1,
            f"{spec.name}: {aliasable} donated leaves are aliasable "
            "but the compiled executable aliases 0 bytes — donation "
            "carries the annotation without shrinking the "
            "argument+temp footprint"))
    return findings


# ================================================================ PT604
def largest_temp(hlo: str) -> Tuple[int, str]:
    """(bytes, description) of the largest single allocated buffer in
    the compiled module, skipping fusion bodies (their intermediates
    stay virtual) and non-allocating opcodes."""
    from paddle_tpu.analysis.shard_audit import _shape_bytes
    best, what = 0, ""
    in_fused = False
    for line in hlo.splitlines():
        if line and not line[0].isspace():
            # a computation header: %name (args) -> result {  /  ENTRY
            in_fused = "fused_computation" in line
            continue
        if in_fused:
            continue
        m = _HLO_INSTR_RE.match(line)
        if m is None:
            continue
        shape_txt, op = m.group(1), m.group(2)
        if op in _NON_ALLOC_OPS or op.endswith("-done"):
            continue
        # an async -start result tuple carries BOTH the operand and
        # output buffers; count only the output half, so a sync<->
        # async spelling flip cannot double-count into a false PT604
        # (the same rule pass 4's byte accounting applies)
        nbytes = _shape_bytes(shape_txt,
                              async_start=op.endswith("-start"))
        if nbytes > best:
            best, what = nbytes, f"{op} -> {shape_txt.strip()}"
    return best, what


def temp_findings(cp: CompiledProgram,
                  manifest: Dict[str, int]) -> List[Finding]:
    threshold = int(max(manifest["param_bytes"], BIG_BYTES) * PACK_SLACK)
    nbytes, what = largest_temp(cp.hlo)
    if nbytes > threshold:
        return [Finding(
            "PT604", cp.spec.anchor, 1,
            f"{cp.spec.name}: single temp buffer of {nbytes} bytes "
            f"({what}) exceeds the program's total per-device param "
            f"bytes ({manifest['param_bytes']}, floor {BIG_BYTES}, "
            f"pack slack {PACK_SLACK}) — "
            "the full-gather-materialization smell; the program "
            "materializes more than one full copy of its state in "
            "one buffer")]
    return []


# ================================================================ PT605
def reconcile_findings(cp: CompiledProgram,
                       manifest: Dict[str, int]) -> List[Finding]:
    """Static-vs-runtime agreement: the compiled manifest's role bytes
    must equal ``utils/profiler.memory_stats`` on the same state. The
    profiler reads the ARRAYS' shardings; the manifest reads the
    PARTITIONER's — when they disagree, either the profiler lies to
    the bench/admission path or the compiled placement drifted."""
    from paddle_tpu.utils.profiler import memory_stats
    spec = cp.spec
    findings: List[Finding] = []
    roles = {r: argnum for r, argnum, _p in spec.mem_roles}
    params = spec.args[roles["params"]] if "params" in roles else {}
    opt_state = (spec.args[roles["opt_slots"]]
                 if "opt_slots" in roles else None)
    # activations: only the leaves the executable CONSUMES — a feed
    # field jit prunes (serving feeds carry label slots _infer never
    # reads) holds no device bytes, and the profiler must be handed
    # the same live set or the comparison measures the feeder, not
    # the program
    act_argnums = {argnum for r, argnum, _p in spec.mem_roles
                   if r == "acts"}
    acts = [leaf for flat_idx, argnum, _path, leaf, _s in _leaf_rows(cp)
            if argnum in act_argnums and flat_idx is not None]
    stats = memory_stats(params, opt_state,
                         activations=acts or None)
    pairs = [("param_bytes", "param_bytes_per_device", "params" in roles),
             ("slot_bytes", "slot_bytes_per_device",
              "opt_slots" in roles),
             ("act_bytes", "act_bytes_per_device", bool(acts))]
    for mkey, skey, declared in pairs:
        if not declared:
            continue
        if manifest[mkey] != stats.get(skey):
            findings.append(Finding(
                "PT605", spec.anchor, 1,
                f"{spec.name}: manifest {mkey}={manifest[mkey]} but "
                f"utils/profiler.memory_stats reports {skey}="
                f"{stats.get(skey)} on the same state — the static "
                "audit and the runtime accounting disagree; one side "
                "drifted (the profiler feeds the bench and the "
                "admission path, the manifest feeds the ratchet)"))
    return findings


# ============================================================== the pass
def audit_memory(cp: CompiledProgram, entries: List[MemBudgetEntry],
                 budget_rel: str, log=None
                 ) -> Tuple[List[Finding], List[int], Dict[str, int]]:
    """All pass-5 checks for one compiled program."""
    manifest = memory_manifest(cp)
    findings, used = check_mem_budget(cp.spec.name, manifest, entries,
                                      cp.spec.anchor, budget_rel)
    findings.extend(scaling_findings(cp))
    findings.extend(donation_findings(cp, manifest))
    findings.extend(temp_findings(cp, manifest))
    findings.extend(reconcile_findings(cp, manifest))
    if log:
        log(f"  {cp.spec.name}: {format_mem_manifest(manifest)}")
    return findings, used, manifest


def run_pass5(root: Optional[str] = None, log=print,
              budget_path: Optional[str] = None,
              programs: Optional[List[CompiledProgram]] = None
              ) -> Tuple[List[Finding], Dict[str, Dict[str, int]]]:
    """Audit every compiled program's per-device memory manifest
    against the committed budget. Returns ``(findings, manifests)`` —
    the manifests ride ``--json`` as the ``MEM_*`` snapshot family.
    Pass ``programs`` from ``shard_audit.compile_programs`` to reuse
    pass 4's compiles (the CLI does)."""
    budget_path = budget_path or default_budget_path()
    budget_rel = os.path.relpath(
        budget_path, root or os.getcwd()).replace(os.sep, "/")
    entries = load_mem_budget(budget_path)
    findings: List[Finding] = []
    manifests: Dict[str, Dict[str, int]] = {}
    used: set = set()
    for cp in programs if programs is not None else compile_programs():
        fs, u, manifest = audit_memory(cp, entries, budget_rel, log=log)
        findings.extend(fs)
        used.update(u)
        manifests[cp.spec.name] = manifest
    findings.extend(stale_mem_budget_findings(entries, used, budget_rel))
    return findings, manifests
