"""Baseline (suppression file) handling for graftlint.

``paddle_tpu/analysis/baseline.toml`` may park known findings so a rule
can land before its last violation is fixed. Policy (enforced by
``tests/test_lint_clean.py``): **the baseline must stay empty or
shrink** — every entry carries a reason and an owner-visible rule id,
and new violations can never be baselined silently (the lint fails
first).

Format (a TOML subset parsed here so the py3.10 container needs no
third-party toml package):

    [[suppress]]
    rule = "PT104"
    path = "paddle_tpu/models/gan.py"
    line = 78            # optional: any line in the file when absent
    reason = "why this is parked, and the issue that will unpark it"
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Tuple

from paddle_tpu.analysis.findings import RULE_BY_NAME, Finding

_KV_RE = re.compile(
    r"^\s*([A-Za-z_][A-Za-z0-9_]*)\s*=\s*(\"([^\"]*)\"|'([^']*)'|(\d+))"
    r"\s*(#.*)?$")


def parse_toml_tables(path: str, label: str, header: str, factory,
                      int_keys=(), str_keys=()):
    """Shared TOML-subset array-of-tables parser (the py3.10 container
    has no tomllib): ``[[header]]`` rows of ``key = "str" | int``
    pairs. Used by both suppression files — the baseline here and
    ``shard_audit``'s comm budget — so a parser fix lands in one
    place. Keys outside ``int_keys``/``str_keys`` are ignored (forward
    compatible); a key before the first table or an unparseable line
    raises ``ValueError`` naming ``label``."""
    entries = []
    current = None
    for raw in open(path, encoding="utf-8"):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line == header:
            current = factory()
            entries.append(current)
            continue
        m = _KV_RE.match(raw)
        if m and current is not None:
            key = m.group(1)
            val = m.group(3) if m.group(3) is not None else (
                m.group(4) if m.group(4) is not None else m.group(5))
            if key in int_keys:
                setattr(current, key, int(val))
            elif key in str_keys:
                setattr(current, key, val)
            continue
        if m and current is None:
            raise ValueError(
                f"{label} {path}: key outside a {header} table: "
                f"{line!r}")
        raise ValueError(f"{label} {path}: unparseable line {line!r}")
    return entries


class BaselineEntry:
    __slots__ = ("rule", "path", "line", "reason")

    def __init__(self, rule: str = "", path: str = "",
                 line: Optional[int] = None, reason: str = ""):
        self.rule = rule
        self.path = path
        self.line = line
        self.reason = reason

    def matches(self, f: Finding) -> bool:
        rule = RULE_BY_NAME.get(self.rule, self.rule)
        if rule != f.rule:
            return False
        if self.path and self.path != f.path:
            return False
        if self.line is not None and self.line != f.line:
            return False
        return True


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.toml")


def load_baseline(path: Optional[str] = None) -> List[BaselineEntry]:
    path = path or default_baseline_path()
    if not os.path.exists(path):
        return []
    entries = parse_toml_tables(
        path, "baseline", "[[suppress]]", BaselineEntry,
        int_keys=("line",), str_keys=("rule", "path", "reason"))
    for e in entries:
        if not e.rule or not e.reason:
            raise ValueError(
                f"baseline {path}: every [[suppress]] needs rule= and "
                "reason=")
    return entries


def apply_baseline(findings: List[Finding],
                   entries: List[BaselineEntry]
                   ) -> Tuple[List[Finding], int, List[BaselineEntry]]:
    """(kept-findings, suppressed-count, stale-entries). A stale entry
    matches nothing — it must be deleted (the baseline only shrinks)."""
    used = [False] * len(entries)
    kept: List[Finding] = []
    suppressed = 0
    for f in findings:
        hit = False
        for i, e in enumerate(entries):
            if e.matches(f):
                used[i] = True
                hit = True
        if hit:
            suppressed += 1
        else:
            kept.append(f)
    stale = [e for i, e in enumerate(entries) if not used[i]]
    return kept, suppressed, stale
