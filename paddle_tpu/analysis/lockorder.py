"""Pass 3 — static lock-acquisition graph over the threaded modules.

The threaded surface this repo grew (serving batcher, master RPC +
heartbeat, background checkpoint writers, prefetch) is exactly where
PR 6's review found real bugs (the ``MasterClient`` socket-desync-
under-lock cross-wiring). A deadlock needs two ingredients a linter can
see statically: two locks, and two code paths acquiring them in
opposite orders. This pass builds the acquisition graph and fails on
cycles (PT301) and on same-lock re-acquisition of a non-reentrant lock
along one call path (PT302).

Model:

- **Lock identities** are ``module.Class.attr`` for ``self.attr =
  threading.Lock()/RLock()/Condition(...)`` assignments.
  ``Condition(self._lock)`` aliases the underlying lock (one identity).
- **Acquisitions** are ``with self.attr:`` blocks (and
  ``self.attr.acquire()`` calls) inside methods of the owning class.
- **Call edges** resolve ``self.m()`` to the same class,
  ``self.attr.m()`` through attribute types recorded from ``__init__``
  assignments / annotations (``self.metrics = ServingMetrics()``), and
  bare names to module functions. Unresolvable calls (callbacks,
  duck-typed parameters) contribute no edges — the runtime tracker
  (``paddle_tpu.testing.lockcheck``) covers those dynamically.
- Holding lock A while reaching (transitively) an acquisition of lock
  B adds edge A -> B. A cycle in the graph = order inversion.

The default scope is the five threaded modules plus the classes they
lock through (metrics, chaos, stat registries).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from paddle_tpu.analysis.findings import Finding

# the threaded modules the tentpole names (r12: five; r13 adds the
# replica router — health thread + per-request dispatch/hedge threads;
# r14 adds the replica supervisor — monitor thread + scale/shutdown
# callers over one bookkeeping lock),
# plus lock-holding classes they call into while holding their own locks
DEFAULT_MODULES = (
    "paddle_tpu/serving/batcher.py",
    "paddle_tpu/serving/router.py",
    "paddle_tpu/serving/supervisor.py",
    "paddle_tpu/dist/master.py",
    "paddle_tpu/dist/checkpoint.py",
    "paddle_tpu/trainer/checkpoint.py",
    "paddle_tpu/data/prefetch.py",
    # supporting lock owners reachable from the above
    "paddle_tpu/serving/metrics.py",
    "paddle_tpu/testing/chaos.py",
    "paddle_tpu/utils/stat.py",
    "paddle_tpu/native/__init__.py",
    # the observability plane (r15): the tracer's span-buffer lock and
    # the metrics registry's provider-table lock are pinned EDGE-FREE
    # (tests/test_lint_clean.py) — obs code must never call back into
    # a subsystem while holding them, and subsystems record spans only
    # outside their own locks. The flight ring is deliberately
    # lock-free (GIL-atomic deque), so it cannot appear here at all.
    "paddle_tpu/obs/trace.py",
    "paddle_tpu/obs/flight.py",
    "paddle_tpu/obs/registry.py",
    # the training-health plane (r16): the event-timeline writer's
    # queue lock and the health monitor's snapshot lock join the same
    # edge-free pin — serialization/file I/O happen on the writer
    # thread outside the lock, and the monitor never appends to the
    # timeline / records flight events under its own lock.
    "paddle_tpu/obs/events.py",
    "paddle_tpu/obs/health.py",
    # the online loop (r20): the replay writer's append lock is the one
    # new lock — the chaos hit fires UNDER it (replay -> chaos, the
    # same precedent as master -> chaos), and sealing never calls out
    # of the module. The tailer's scanner thread and the publisher are
    # deliberately lock-free (master's RLock + GIL-atomic state), so
    # they contribute scope, not locks.
    "paddle_tpu/online/replay.py",
    "paddle_tpu/online/tailer.py",
    "paddle_tpu/online/publish.py",
    "paddle_tpu/online/loop.py",
)

_LOCK_CTORS = {"Lock": False, "RLock": True}  # name -> reentrant


from paddle_tpu.analysis._astutil import dotted as _dotted


class LockInfo:
    __slots__ = ("ident", "reentrant", "path", "line")

    def __init__(self, ident: str, reentrant: bool, path: str, line: int):
        self.ident = ident
        self.reentrant = reentrant
        self.path = path
        self.line = line


class MethodInfo:
    """Per-method facts: lock acquisitions (with held-set context) and
    calls (with held-set context). ``module``/``cls`` are carried
    explicitly — deriving them by splitting the qual mis-parses
    module-level functions in dotted packages."""

    def __init__(self, qual: str, module: str = "",
                 cls: Optional[str] = None):
        self.qual = qual  # module.Class.method or module.function
        self.module = module
        self.cls = cls    # module.Class, or None for module functions
        # (held-locks-tuple, lock-ident, line)
        self.acquires: List[Tuple[Tuple[str, ...], str, int]] = []
        # (held-locks-tuple, callee-token, line); callee-token is
        # "self.m", "self.attr.m", or a bare dotted name
        self.calls: List[Tuple[Tuple[str, ...], str, int]] = []


class LockOrderChecker:
    def __init__(self, root: str,
                 modules: Sequence[str] = DEFAULT_MODULES):
        self.root = root
        self.modules = list(modules)
        self.locks: Dict[str, LockInfo] = {}
        self.methods: Dict[str, MethodInfo] = {}
        # class name -> module.Class (for attr-type resolution); last
        # writer wins which is fine inside this closed module set
        self.class_qual: Dict[str, str] = {}
        # module.Class -> {attr -> class-name}
        self.attr_types: Dict[str, Dict[str, str]] = {}
        # module.Class -> {lock-attr -> lock-ident}
        self.class_locks: Dict[str, Dict[str, str]] = {}
        self.findings: List[Finding] = []

    # ------------------------------------------------------- collection
    def load(self):
        self._trees = []
        for rel in self.modules:
            path = os.path.join(self.root, rel)
            if not os.path.exists(path):
                continue
            source = open(path, encoding="utf-8").read()
            tree = ast.parse(source, filename=path)
            modname = rel[:-3].replace("/", ".").replace(
                ".__init__", "")
            self._trees.append((tree, modname, rel))
        # phase 1: register every class in the set (so cross-module
        # attribute typing — batcher's ServingMetrics — resolves no
        # matter the module order); phase 2: scan attribute assigns
        for tree, modname, _rel in self._trees:
            for node in tree.body:
                if isinstance(node, ast.ClassDef):
                    qual = f"{modname}.{node.name}"
                    self.class_qual[node.name] = qual
                    self.attr_types.setdefault(qual, {})
                    self.class_locks.setdefault(qual, {})
        for tree, modname, rel in self._trees:
            for node in tree.body:
                if isinstance(node, ast.ClassDef):
                    qual = f"{modname}.{node.name}"
                    for sub in ast.walk(node):
                        self._scan_attr_assign(sub, qual, rel)
        self._collect_bodies()

    def _scan_attr_assign(self, node: ast.AST, class_qual: str,
                          rel: str):
        """self.X = <ctor> assignments: lock attrs and typed attrs."""
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            if isinstance(node, ast.AnnAssign) and node.annotation is \
                    not None and isinstance(node.target, ast.Attribute) \
                    and _dotted(node.target.value) == "self":
                ann = ast.unparse(node.annotation)
                for cname in self.class_qual:
                    if cname in ann:
                        self.attr_types[class_qual][
                            node.target.attr] = cname
            return
        tgt = node.targets[0]
        if not (isinstance(tgt, ast.Attribute)
                and _dotted(tgt.value) == "self"):
            return
        val = node.value
        if not isinstance(val, ast.Call):
            # `self.metrics = metrics or ServingMetrics()` shells: any
            # ctor call of an analyzed class types the attribute
            for sub in ast.walk(val):
                if isinstance(sub, ast.Call):
                    cd = (_dotted(sub.func) or "").split(".")[-1]
                    if cd in self.class_qual:
                        self.attr_types[class_qual][tgt.attr] = cd
                        return
            return
        d = _dotted(val.func) or ""
        leaf = d.split(".")[-1]
        if leaf in _LOCK_CTORS and ("threading" in d or d == leaf):
            ident = f"{class_qual}.{tgt.attr}"
            self.locks[ident] = LockInfo(ident, _LOCK_CTORS[leaf],
                                         rel, node.lineno)
            self.class_locks[class_qual][tgt.attr] = ident
        elif leaf == "Condition":
            # Condition(self._lock) aliases the lock it wraps;
            # Condition() owns a fresh (reentrant) RLock
            if val.args and isinstance(val.args[0], ast.Attribute) \
                    and _dotted(val.args[0].value) == "self":
                base = val.args[0].attr
                base_ident = self.class_locks[class_qual].get(base)
                if base_ident is not None:
                    self.class_locks[class_qual][tgt.attr] = base_ident
                    return
            ident = f"{class_qual}.{tgt.attr}"
            self.locks[ident] = LockInfo(ident, True, rel, node.lineno)
            self.class_locks[class_qual][tgt.attr] = ident
        else:
            # typed attribute (self.metrics = ServingMetrics(...), also
            # `metrics or ServingMetrics()` shells)
            for sub in ast.walk(val):
                if isinstance(sub, ast.Call):
                    cd = (_dotted(sub.func) or "").split(".")[-1]
                    if cd in self.class_qual:
                        self.attr_types[class_qual][tgt.attr] = cd
                        return

    # ----------------------------------------------------- method bodies
    def _collect_bodies(self):
        for tree, modname, rel in self._trees:
            for node in tree.body:
                if isinstance(node, ast.ClassDef):
                    qual = f"{modname}.{node.name}"
                    for item in node.body:
                        if isinstance(item, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
                            self._scan_method(item, qual, rel)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    mi = MethodInfo(f"{modname}.{node.name}",
                                    module=modname)
                    self._walk_body(node.body, (), None, mi, rel)
                    self.methods[mi.qual] = mi

    def _scan_method(self, fn: ast.AST, class_qual: str, rel: str):
        mi = MethodInfo(f"{class_qual}.{fn.name}",
                        module=class_qual.rsplit(".", 1)[0],
                        cls=class_qual)
        self._walk_body(fn.body, (), class_qual, mi, rel)
        self.methods[mi.qual] = mi

    def _lock_of_expr(self, expr: ast.AST,
                      class_qual: Optional[str]) -> Optional[str]:
        if class_qual is None:
            return None
        if isinstance(expr, ast.Attribute) \
                and _dotted(expr.value) == "self":
            return self.class_locks.get(class_qual, {}).get(expr.attr)
        return None

    def _walk_body(self, body: List[ast.stmt], held: Tuple[str, ...],
                   class_qual: Optional[str], mi: MethodInfo, rel: str):
        """Recurse through EVERY compound statement carrying the held
        set — a `with self._lock:` nested under if/try/for/while (i.e.
        virtually every worker-loop lock site) must be seen with its
        true context, or the graph silently undercounts."""
        for stmt in body:
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                new_held = held
                for item in stmt.items:
                    ident = self._lock_of_expr(item.context_expr,
                                               class_qual)
                    if ident is not None:
                        mi.acquires.append((new_held, ident,
                                            stmt.lineno))
                        new_held = new_held + (ident,)
                    else:
                        # scanned with the held set AS OF this item —
                        # `with self._lock, self._make_cm():` runs
                        # _make_cm() while the lock is already held
                        self._scan_exprs([item.context_expr], new_held,
                                         mi, class_qual=class_qual)
                self._walk_body(stmt.body, new_held, class_qual, mi,
                                rel)
                continue
            # nested defs: their bodies run LATER, possibly on another
            # thread (Thread targets), under unknown lock context —
            # record them as their OWN method ("<locals>" qual) so a
            # synchronous bare-name call still resolves to them, but a
            # closure handed to a Thread contributes nothing to the
            # enclosing method's transitive lockset
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                sub = MethodInfo(f"{mi.qual}.<locals>.{stmt.name}",
                                 module=mi.module, cls=mi.cls)
                self._walk_body(stmt.body, (), class_qual, sub, rel)
                self.methods[sub.qual] = sub
                continue
            if isinstance(stmt, ast.If):
                self._scan_exprs([stmt.test], held, mi,
                                 class_qual=class_qual)
                self._walk_body(stmt.body, held, class_qual, mi, rel)
                self._walk_body(stmt.orelse, held, class_qual, mi, rel)
                continue
            if isinstance(stmt, ast.While):
                self._scan_exprs([stmt.test], held, mi,
                                 class_qual=class_qual)
                self._walk_body(stmt.body, held, class_qual, mi, rel)
                self._walk_body(stmt.orelse, held, class_qual, mi, rel)
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._scan_exprs([stmt.iter], held, mi,
                                 class_qual=class_qual)
                self._walk_body(stmt.body, held, class_qual, mi, rel)
                self._walk_body(stmt.orelse, held, class_qual, mi, rel)
                continue
            if isinstance(stmt, ast.Try):
                self._walk_body(stmt.body, held, class_qual, mi, rel)
                for handler in stmt.handlers:
                    self._walk_body(handler.body, held, class_qual, mi,
                                    rel)
                self._walk_body(stmt.orelse, held, class_qual, mi, rel)
                self._walk_body(stmt.finalbody, held, class_qual, mi,
                                rel)
                continue
            self._scan_exprs([stmt], held, mi, class_qual=class_qual)

    def _scan_exprs(self, nodes, held: Tuple[str, ...], mi: MethodInfo,
                    class_qual: Optional[str] = None):
        """Calls (and explicit .acquire()s) inside leaf statements and
        guard expressions, recorded with the current held set."""
        for root in nodes:
            if root is None:
                continue
            for node in ast.walk(root):
                if isinstance(node, ast.Call):
                    d = _dotted(node.func)
                    if d is None:
                        continue
                    if d.endswith(".acquire"):
                        ident = self._lock_of_expr(
                            node.func.value, class_qual)
                        if ident is not None:
                            mi.acquires.append((held, ident,
                                                node.lineno))
                            continue
                    mi.calls.append((held, d, node.lineno))

    # known module-global singletons whose methods run under the
    # caller's locks (the chaos plane is hit from inside several
    # with-blocks); token prefix -> class name
    SINGLETONS = {
        "_chaos._ACTIVE": "FaultPlan",
        "chaos._ACTIVE": "FaultPlan",
        # the obs plane's module globals: calls through them from
        # inside a with-block DO count as lock acquisitions of the
        # tracer lock, which is how the edge-free pin is enforceable
        # rather than vacuous (the flight recorder has no lock — see
        # obs/flight.py — so _flight._ACTIVE maps to a lockless class)
        "_trace._TRACER": "Tracer",
        "trace._TRACER": "Tracer",
        "_flight._ACTIVE": "FlightRecorder",
        "flight._ACTIVE": "FlightRecorder",
    }

    # ------------------------------------------------------- resolution
    def _resolve_callee(self, token: str,
                        caller: str) -> Optional[str]:
        """Callee token -> method qual, within the analyzed set."""
        for prefix, cname in self.SINGLETONS.items():
            if token.startswith(prefix + ".") and cname in \
                    self.class_qual:
                meth = token[len(prefix) + 1:]
                q = f"{self.class_qual[cname]}.{meth}"
                if q in self.methods:
                    return q
        parts = token.split(".")
        caller_mi = self.methods.get(caller)
        caller_mod = caller_mi.module if caller_mi else ""
        caller_class = caller_mi.cls if caller_mi else None
        if parts[0] == "self" and caller_class is not None:
            if len(parts) == 2:
                q = f"{caller_class}.{parts[1]}"
                return q if q in self.methods else None
            if len(parts) == 3:
                cname = self.attr_types.get(caller_class, {}).get(
                    parts[1])
                if cname is not None:
                    q = f"{self.class_qual[cname]}.{parts[2]}"
                    return q if q in self.methods else None
            return None
        if len(parts) == 1:
            # a synchronous call of a nested def shadows the module
            # namespace — try the caller's locals first
            q = f"{caller}.<locals>.{parts[0]}"
            if q in self.methods:
                return q
            q = f"{caller_mod}.{parts[0]}"
            return q if q in self.methods else None
        return None

    def _transitive_locks(self, qual: str,
                          seen: Optional[Set[str]] = None
                          ) -> Set[Tuple[str, int, str]]:
        """Locks acquired by ``qual`` or anything it calls:
        {(lock-ident, line, at-method)}."""
        if seen is None:
            seen = set()
        if qual in seen:
            return set()
        seen.add(qual)
        out: Set[Tuple[str, int, str]] = set()
        mi = self.methods.get(qual)
        if mi is None:
            return out
        for _held, ident, line in mi.acquires:
            out.add((ident, line, qual))
        for _held, token, _line in mi.calls:
            callee = self._resolve_callee(token, qual)
            if callee is not None:
                out |= self._transitive_locks(callee, seen)
        return out

    # ----------------------------------------------------------- check
    def run(self) -> List[Finding]:
        self.load()
        # edge (A, B) -> evidence string
        edges: Dict[Tuple[str, str], str] = {}

        def add_edge(a: str, b: str, where: str, line: int):
            if a == b:
                info = self.locks.get(a)
                if info is not None and not info.reentrant:
                    rel = self._rel_of(where)
                    self.findings.append(Finding(
                        "PT302", rel, line,
                        f"non-reentrant lock {a} can be re-acquired "
                        f"while already held (path through {where})"))
                return
            edges.setdefault((a, b),
                             f"{where}:{line}")

        for qual, mi in self.methods.items():
            for held, ident, line in mi.acquires:
                for h in held:
                    add_edge(h, ident, qual, line)
            for held, token, line in mi.calls:
                if not held:
                    continue
                callee = self._resolve_callee(token, qual)
                if callee is None:
                    continue
                for ident, lline, lqual in self._transitive_locks(
                        callee):
                    for h in held:
                        add_edge(h, ident, f"{qual} -> {lqual}", line)

        # cycle detection over the lock graph
        adj: Dict[str, List[str]] = {}
        for (a, b) in edges:
            adj.setdefault(a, []).append(b)
        color: Dict[str, int] = {}
        stack: List[str] = []

        def dfs(u: str):
            color[u] = 1
            stack.append(u)
            for v in adj.get(u, []):
                if color.get(v, 0) == 0:
                    dfs(v)
                elif color.get(v) == 1:
                    cyc = stack[stack.index(v):] + [v]
                    ev = "; ".join(
                        f"{x}->{y} at {edges[(x, y)]}"
                        for x, y in zip(cyc, cyc[1:]))
                    first = self.locks.get(cyc[0])
                    self.findings.append(Finding(
                        "PT301",
                        first.path if first else "<unknown>",
                        first.line if first else 1,
                        "lock-order inversion: "
                        + " -> ".join(cyc) + f" ({ev})"))
            stack.pop()
            color[u] = 2

        for node in sorted(adj):
            if color.get(node, 0) == 0:
                dfs(node)

        self.edges = edges
        return self.findings

    def _rel_of(self, where: str) -> str:
        mod = where.split(" -> ")[-1]
        for ident, info in self.locks.items():
            if mod.startswith(ident.rsplit(".", 1)[0].rsplit(".", 1)[0]):
                return info.path
        return self.modules[0]

    # ------------------------------------------------------- reporting
    def describe(self) -> str:
        lines = [f"locks: {len(self.locks)}"]
        for ident in sorted(self.locks):
            info = self.locks[ident]
            kind = "RLock/Condition" if info.reentrant else "Lock"
            lines.append(f"  {ident} ({kind}) {info.path}:{info.line}")
        lines.append(f"acquisition-order edges: {len(self.edges)}")
        for (a, b), ev in sorted(self.edges.items()):
            lines.append(f"  {a} -> {b}  [{ev}]")
        return "\n".join(lines)


def run_pass3(root: str,
              modules: Sequence[str] = DEFAULT_MODULES
              ) -> Tuple[List[Finding], "LockOrderChecker"]:
    checker = LockOrderChecker(root, modules)
    findings = checker.run()
    return findings, checker
