"""graftlint: framework-aware static analysis for paddle_tpu.

Four passes (``python -m paddle_tpu.analysis`` runs them all):

1. **AST invariant lints** (``ast_lints.py``) — pure source analysis
   over ``paddle_tpu/``, ``tests/``, ``tools/``: closure-captured
   arrays in jitted functions, masks cast below f32, ``jnp.pad`` in
   bit-exact pack paths, unguarded persistent jits on hot paths, broad
   ``pkill -f`` patterns, and layer-grad-matrix coverage.
2. **Jaxpr/lowering audit** (``jaxpr_audit.py``) — traces the driver
   entry (``__graft_entry__.entry()``), a representative train step,
   and the serving warm path; asserts no model-sized embedded
   constants, full donation of donatable buffers, and mask dtypes
   surviving as f32 through the traced program.
3. **Lock-order checker** (``lockorder.py``) — a static
   lock-acquisition graph over the threaded modules (serving batcher,
   master, checkpoint writers, prefetch) with cycle detection; the
   runtime twin is ``paddle_tpu.testing.lockcheck``.
4. **Sharding & collective audit** (``shard_audit.py``) — compiles
   the real parallel programs (dp train, zero1, GPipe pipeline, TP
   embedding, ring attention, serving warm path) on the 8-device
   virtual mesh and pins their collective manifest against
   ``comm_budget.toml`` (only-shrinks), plus unintended-replication,
   unpinned-pack, reshard-copy, and ``rule_for``-table checks.

Plus the evidence-artifact schema check (``bench_schema.py``:
``BENCH_*``/``MULTICHIP_*``/``ACCURACY_*.json``) that ``tools/lint.py``
runs alongside.

Findings carry file:line + stable rule ids (``findings.RULES``); the
suppression policy and rule catalog live in ``docs/static_analysis.md``.
``analysis/baseline.toml`` may park known findings — it must stay empty
or shrink (enforced by ``tests/test_lint_clean.py``).
"""

from paddle_tpu.analysis.findings import (Finding, RULES,  # noqa: F401
                                          format_report, rule_counts)
