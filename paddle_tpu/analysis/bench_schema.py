"""Evidence-artifact schema check (PT401): ``BENCH_*.json``,
``MULTICHIP_*.json``, ``ACCURACY_*.json``, ``MEM_*.json`` and
``HEALTH_*.json``.

These artifacts are the evidence trail (perf best-of-R discipline,
multichip dryruns, real-corpus accuracy runs). A malformed artifact —
truncated JSON, a NaN ratio, an A/B metric missing its sides — should
fail at *lint* time, not at ROADMAP-review time when the run that
produced it is long gone.

The artifact FAMILY is keyed by filename (content sniffing would let a
truncated artifact of one family quietly validate against another's
looser schema):

- ``MULTICHIP_*``: ``{"n_devices": int, "rc": int, "ok": bool,
  "skipped": bool, "tail": str}`` — the ``dryrun_multichip`` capture;
  the tail is the re-checkable evidence and must be present even on a
  skip.
- ``ACCURACY_*``: ``{"platform": str, ...}`` plus at least one named
  run section (a dict) — an accuracy artifact with no run sections
  recorded nothing.
- ``TRACE_*`` (committed distributed-trace evidence, e.g. the
  ``bench.py --fleet`` failover trace): ``{"spans": [...]}`` with a
  NON-EMPTY span list, every span carrying string ``trace_id`` /
  ``span_id`` / ``name``, numeric ``ts`` and ``dur_ms >= 0``, spans
  sorted by ``ts`` (monotone file order), and every non-null
  ``parent_id`` resolving to another span's ``span_id`` in the same
  file — a trace whose parents dangle reconstructs nothing.
- ``HEALTH_*`` (committed training-health timelines: the sampled
  run `bench.py --health` writes, or a snapshot of an
  ``obs/events.py`` JSONL bundled as one object):
  ``{"run": str, "period": int >= 0, "events": [...]}`` with a
  NON-EMPTY events list, every event carrying an int ``step >= 0``
  in monotone non-decreasing order and a finite numeric ``loss`` —
  a timeline with no steps, shuffled steps, or NaN losses recorded
  nothing diffable (``tools/healthview.py`` is the consumer).
- ``MEM_*`` (optional trend snapshots of graftlint pass 5's
  per-program per-device byte manifests, emitted by
  ``python -m paddle_tpu.analysis --json | jq .mem_manifest``):
  ``{"programs": {name: {field: int >= 0, ...}, ...}}`` with a
  non-empty programs map — a malformed snapshot is a finding, not a
  silently unplottable file.
- ``WORKLOAD_*`` (committed request traces, the ``bench.py
  --autotune`` record / ``tests/test_workload_replay.py`` replay pair):
  ``{"workload": str, "version": 1, "n_events": int, "duration_s":
  num >= 0, "events": [...]}`` with a NON-EMPTY events list whose
  length matches ``n_events``, every event carrying the full replay
  key set (``serving/workload.py:EVENT_KEYS``), a ``kind`` in
  {score, generate}, and numeric ``t >= 0`` in monotone non-decreasing
  order — a trace that cannot be re-offered at its recorded offsets
  tunes nothing.
- ``BENCH_*`` (shape-sniffed among its real generations):
  **metric style** (r07+, also BENCH_LIVE) ``{"metric": str,
  "platform": str, ...}`` where every ``*_vs_*`` ratio key must be a
  finite number (or null when a side was skipped) with both A/B sides
  present; **harness style** (r01–r05) ``{"n": ..., "cmd": str, "rc":
  int, ...}``; **watcher style** (r06) ``{"round": ..., "cmd": ...,
  "parsed": dict, ...}``. Metric-style artifacts whose metric starts
  with ``serving_fleet`` (BENCH_r13, the kill-and-respawn bench) must
  additionally carry the cold-start A/B sides (``cold_start_live_ms`` /
  ``cold_start_cache_ms``), ``fleet_p99_ms``, and the
  ``fleet_failovers_total`` / ``fleet_failed_non_shed`` counters — the
  failover and zero-drop evidence. Metrics starting with
  ``serving_fleet_autoscale`` (BENCH_r14, the self-operating fleet)
  must FURTHER carry ``autoscale_replica_trajectory`` (a non-empty list
  of replica counts — did the count follow the ramp inside the
  bounds?), ``autoscale_p99_ms``, and ``fleet_failed_non_shed`` summed
  across rounds. Metrics starting with ``overlap`` (BENCH_r18, the
  FSDP gather-overlap x fused-kernel 2x2) must carry the step-time A/B
  sides (``overlap_on_steps_per_sec`` / ``overlap_off_steps_per_sec``),
  the int exposed-collective counts
  (``exposed_collectives_overlap_on`` / ``..._off``) and the numeric
  exposed-comm fractions (``exposed_comm_frac_overlap_on`` /
  ``..._off``) — the structural overlap evidence. Metrics starting
  with ``serving_quant`` (BENCH_r19, the quantized serving three-way)
  must carry all three precision sides (``quant_fp32_p50_ms`` /
  ``quant_bf16_p50_ms`` / ``quant_int8_p50_ms``), FINITE gate deltas
  (``quant_gate_delta_bf16`` / ``quant_gate_delta_int8``) and the
  bool ``quant_gate_passed`` — an un-gated speedup is not evidence.
  Metrics starting with ``serving_autotune`` (BENCH_r21, the
  self-tuning loop) must carry ``autotune_workloads`` — a non-empty
  list of ``WORKLOAD_*.json`` filenames each resolving to a file NEXT
  TO the artifact (the trace/score JOIN: a tune score whose trace is
  gone is unreplayable evidence), the per-mix A/B score sides
  (``autotune_<mix>_default_score`` / ``..._tuned_score``), each mix's
  ``autotune_<mix>_replay_drift`` within the declared
  ``autotune_drift_bound``, and the int ``fleet_failed_non_shed``
  summed over every replay. Metrics starting with ``serve_train``
  (BENCH_r20, the online
  learning loop) must carry ``serve_train_error_trajectory`` (a
  non-empty list of finite held-out error numbers, one per published
  version — the does-online-training-actually-learn evidence), the
  int ``fleet_failed_non_shed`` summed over every round (the fleet
  stayed up through the hot-swaps), and the int ``publishes_total`` /
  ``rollbacks_total`` counters (how many versions went live, and how
  many refused artifacts rolled back to the incumbent).

Everything must parse as one JSON object with finite numbers
throughout (NaN/Infinity are emitted by a crashed averaging step and
json.dumps happily writes them).
"""

from __future__ import annotations

import glob
import json
import math
import os
from typing import Any, List, Optional, Sequence

from paddle_tpu.analysis.findings import Finding


def _walk_numbers(obj: Any, path: str = "$"):
    if isinstance(obj, dict):
        for k, v in obj.items():
            yield from _walk_numbers(v, f"{path}.{k}")
    elif isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            yield from _walk_numbers(v, f"{path}[{i}]")
    elif isinstance(obj, float):
        yield path, obj


def check_bench_file(path: str, rel: str) -> List[Finding]:
    findings: List[Finding] = []

    def bad(msg: str):
        findings.append(Finding("PT401", rel, 1, msg))

    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        bad(f"unparseable bench artifact: {e}")
        return findings
    if not isinstance(data, dict):
        bad(f"bench artifact must be one JSON object, got "
            f"{type(data).__name__}")
        return findings
    # the artifact FAMILY comes from the filename, not sniffed content:
    # a BENCH file whose crashed writer dropped 'metric' but kept
    # 'platform' must fail as an unrecognized bench shape, not
    # quietly validate against the (looser) accuracy schema
    base = os.path.basename(rel)
    if base.startswith("MULTICHIP_"):
        # the dryrun_multichip capture
        if not isinstance(data.get("n_devices"), int) or isinstance(
                data.get("n_devices"), bool):
            bad("multichip artifact missing int 'n_devices'")
        if not isinstance(data.get("rc"), int) or isinstance(
                data.get("rc"), bool):
            bad("multichip artifact missing int 'rc'")
        for key in ("ok", "skipped"):
            if not isinstance(data.get(key), bool):
                bad(f"multichip artifact missing bool {key!r}")
        if not isinstance(data.get("tail"), str):
            bad("multichip artifact missing str 'tail' (the "
                "re-checkable dryrun evidence)")
    elif base.startswith("TRACE_"):
        spans = data.get("spans")
        if not (isinstance(spans, list) and spans):
            bad("trace artifact needs a non-empty 'spans' list")
        else:
            ids = {s.get("span_id") for s in spans
                   if isinstance(s, dict)}
            last_ts = None
            for i, s in enumerate(spans):
                if not isinstance(s, dict):
                    bad(f"span[{i}] must be an object")
                    continue
                for k in ("trace_id", "span_id", "name"):
                    if not (isinstance(s.get(k), str) and s.get(k)):
                        bad(f"span[{i}] missing non-empty str {k!r}")
                ts, dur = s.get("ts"), s.get("dur_ms")
                if not isinstance(ts, (int, float)) or isinstance(
                        ts, bool):
                    bad(f"span[{i}] missing numeric 'ts'")
                    ts = None
                if (not isinstance(dur, (int, float))
                        or isinstance(dur, bool) or dur < 0):
                    bad(f"span[{i}] needs numeric 'dur_ms' >= 0")
                if ts is not None:
                    if last_ts is not None and ts < last_ts:
                        bad(f"span[{i}] breaks monotone file order "
                            f"(ts {ts} < previous {last_ts}) — the "
                            "writer sorts by start time")
                    last_ts = ts
                parent = s.get("parent_id")
                if parent is not None and parent not in ids:
                    bad(f"span[{i}] parent_id {parent!r} resolves to "
                        "no span in this file — a dangling parent "
                        "reconstructs nothing")
    elif base.startswith("HEALTH_"):
        # a committed training-health timeline (obs/events.py records
        # bundled as one object; tools/healthview.py renders/diffs it)
        if not (isinstance(data.get("run"), str) and data.get("run")):
            bad("health artifact needs a non-empty str 'run'")
        period = data.get("period")
        if (not isinstance(period, int) or isinstance(period, bool)
                or period < 0):
            bad("health artifact needs int 'period' >= 0 (the stat "
                "cadence the timeline was recorded at)")
        events = data.get("events")
        if not (isinstance(events, list) and events):
            bad("health artifact needs a non-empty 'events' list "
                "(a timeline with no steps recorded nothing)")
        else:
            last_step = None
            for i, e in enumerate(events):
                if not isinstance(e, dict):
                    bad(f"events[{i}] must be an object")
                    continue
                step = e.get("step")
                if (not isinstance(step, int) or isinstance(step, bool)
                        or step < 0):
                    bad(f"events[{i}] missing int 'step' >= 0")
                    step = None
                if step is not None:
                    if last_step is not None and step < last_step:
                        bad(f"events[{i}] breaks monotone step order "
                            f"(step {step} < previous {last_step}) — "
                            "a shuffled timeline diffs nothing")
                    last_step = step
                loss = e.get("loss")
                if (not isinstance(loss, (int, float))
                        or isinstance(loss, bool)):
                    bad(f"events[{i}] missing numeric 'loss'")
                # non-finite losses are caught by the global
                # finite-number walk below, with their exact path
    elif base.startswith("WORKLOAD_"):
        # a committed request trace (serving/workload.py): replayable
        # by construction or a finding — the tuner's scores are only
        # evidence while the trace they came from still re-offers
        from paddle_tpu.serving.workload import (EVENT_KEYS,
                                                 WORKLOAD_VERSION)
        if not (isinstance(data.get("workload"), str)
                and data.get("workload")):
            bad("workload artifact needs a non-empty str 'workload'")
        if data.get("version") != WORKLOAD_VERSION:
            bad(f"workload artifact version {data.get('version')!r} != "
                f"{WORKLOAD_VERSION}")
        events = data.get("events")
        if not (isinstance(events, list) and events):
            bad("workload artifact needs a non-empty 'events' list "
                "(a trace with no offers replays nothing)")
        else:
            if data.get("n_events") != len(events):
                bad(f"workload artifact n_events {data.get('n_events')!r}"
                    f" != {len(events)} events present (truncated?)")
            last_t = None
            for i, e in enumerate(events):
                if not isinstance(e, dict):
                    bad(f"events[{i}] must be an object")
                    continue
                missing = [k for k in EVENT_KEYS if k not in e]
                if missing:
                    bad(f"events[{i}] missing replay key(s) {missing}")
                if e.get("kind") not in ("score", "generate"):
                    bad(f"events[{i}] unknown kind {e.get('kind')!r}")
                t = e.get("t")
                if (not isinstance(t, (int, float))
                        or isinstance(t, bool) or t < 0):
                    bad(f"events[{i}] needs numeric 't' >= 0 (the "
                        "recorded arrival offset)")
                elif last_t is not None and t < last_t:
                    bad(f"events[{i}] breaks monotone arrival order "
                        f"(t {t} < previous {last_t}) — the recorder "
                        "snapshot sorts by offset")
                else:
                    last_t = t
    elif base.startswith("MEM_"):
        # a pass-5 memory-manifest trend snapshot
        progs = data.get("programs")
        if not (isinstance(progs, dict) and progs):
            bad("mem artifact needs a non-empty 'programs' object "
                "(per-program per-device byte manifests)")
        else:
            for name, fields in progs.items():
                if not isinstance(fields, dict) or not fields:
                    bad(f"mem artifact program {name!r} must map to a "
                        "non-empty object of byte fields")
                    continue
                for k, v in fields.items():
                    if (not isinstance(v, int) or isinstance(v, bool)
                            or v < 0):
                        bad(f"mem artifact {name}.{k} must be a "
                            f"non-negative int byte count, got {v!r}")
    elif base.startswith("ACCURACY_"):
        # platform + named run sections
        if not (isinstance(data.get("platform"), str)
                and data.get("platform")):
            bad("accuracy artifact needs a non-empty str 'platform'")
        if not any(isinstance(v, dict) for v in data.values()):
            bad("accuracy artifact has no named run section "
                "(at least one config's results object)")
    elif "metric" in data:
        if not (isinstance(data["metric"], str) and data["metric"]):
            bad("'metric' must be a non-empty string")
        if not isinstance(data.get("platform"), str):
            bad("metric-style artifact missing 'platform'")
        if str(data.get("metric", "")).startswith("serving_fleet"):
            # the r13 fleet artifact (BENCH_r13): kill-and-respawn
            # evidence is only evidence with the cold-start A/B sides,
            # the fleet p99, and the failover/zero-drop counters present
            for k in ("cold_start_live_ms", "cold_start_cache_ms",
                      "fleet_p99_ms"):
                v = data.get(k)
                if not isinstance(v, (int, float)) or isinstance(v, bool):
                    bad(f"fleet artifact missing numeric {k!r}")
            for k in ("fleet_failovers_total", "fleet_failed_non_shed"):
                v = data.get(k)
                if not isinstance(v, int) or isinstance(v, bool):
                    bad(f"fleet artifact missing int {k!r} (the "
                        "failover / zero-drop evidence)")
        if str(data.get("metric", "")).startswith(
                "serving_fleet_autoscale"):
            # the r14 self-operating-fleet generation: an autoscale
            # claim is only evidence with the replica-count TRAJECTORY
            # (did the count actually follow load, inside the bounds?),
            # the p99 under the ramp, and the zero-failed counter
            # SUMMED across rounds (a failing round must not hide
            # behind a best-of)
            traj = data.get("autoscale_replica_trajectory")
            if (not isinstance(traj, list) or not traj
                    or not all(isinstance(n, int)
                               and not isinstance(n, bool)
                               for n in traj)):
                bad("autoscale artifact missing "
                    "'autoscale_replica_trajectory' (non-empty list of "
                    "replica counts — the follow-the-load evidence)")
            v = data.get("autoscale_p99_ms")
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                bad("autoscale artifact missing numeric "
                    "'autoscale_p99_ms' (the bounded-latency evidence)")
            v = data.get("fleet_failed_non_shed")
            if not isinstance(v, int) or isinstance(v, bool):
                bad("autoscale artifact missing int "
                    "'fleet_failed_non_shed' summed across rounds")
        if str(data.get("metric", "")).startswith("serving_quant"):
            # the r19 quantized-serving generation (BENCH_r19): a
            # quantization claim is only evidence with all THREE
            # precision sides, the gate deltas FINITE (the in-bench
            # accuracy gate actually replayed), and the gate verdict
            for k in ("quant_fp32_p50_ms", "quant_bf16_p50_ms",
                      "quant_int8_p50_ms", "quant_gate_delta_bf16",
                      "quant_gate_delta_int8"):
                v = data.get(k)
                if not isinstance(v, (int, float)) or isinstance(v, bool):
                    bad(f"quant artifact missing numeric {k!r} (the "
                        "three-sided A/B + gate-delta evidence)")
            if not isinstance(data.get("quant_gate_passed"), bool):
                bad("quant artifact missing bool 'quant_gate_passed' "
                    "(the in-bench warmup gate verdict)")
        if str(data.get("metric", "")).startswith("serving_autotune"):
            # the r21 self-tuning generation (BENCH_r21): a tune score
            # is only evidence joined to the trace it replayed — the
            # listed WORKLOAD_*.json files must exist beside the
            # artifact and each mix must carry both A/B score sides,
            # its determinism drift inside the declared bound, and the
            # zero-drop counter summed over every replay
            wls = data.get("autotune_workloads")
            if (not isinstance(wls, list) or not wls
                    or not all(isinstance(w, str)
                               and w.startswith("WORKLOAD_")
                               for w in wls)):
                bad("autotune artifact missing 'autotune_workloads' "
                    "(non-empty list of WORKLOAD_*.json filenames — "
                    "the trace/score join)")
            else:
                art_dir = os.path.dirname(os.path.abspath(path))
                for w in wls:
                    if not os.path.exists(os.path.join(art_dir, w)):
                        bad(f"autotune artifact cites trace {w!r} which "
                            "does not exist beside it — an unjoined "
                            "tune score is unreplayable evidence")
            bound = data.get("autotune_drift_bound")
            if not isinstance(bound, (int, float)) or isinstance(
                    bound, bool):
                bad("autotune artifact missing numeric "
                    "'autotune_drift_bound' (the declared score "
                    "tolerance its determinism claim cites)")
            mixes_ = data.get("autotune_mixes")
            if (not isinstance(mixes_, list) or not mixes_
                    or not all(isinstance(m, str) for m in mixes_)):
                bad("autotune artifact missing 'autotune_mixes' "
                    "(non-empty list of mix names)")
            else:
                for m in mixes_:
                    for k in (f"autotune_{m}_default_score",
                              f"autotune_{m}_tuned_score",
                              f"autotune_{m}_replay_drift"):
                        v = data.get(k)
                        if not isinstance(v, (int, float)) or isinstance(
                                v, bool):
                            bad(f"autotune artifact missing numeric "
                                f"{k!r} (the per-mix A/B + determinism "
                                "evidence)")
                    drift = data.get(f"autotune_{m}_replay_drift")
                    if (isinstance(drift, (int, float))
                            and not isinstance(drift, bool)
                            and isinstance(bound, (int, float))
                            and not isinstance(bound, bool)
                            and drift > bound):
                        bad(f"autotune mix {m!r} replay drift {drift} "
                            f"exceeds its own declared bound {bound} — "
                            "the determinism claim fails its artifact")
            v = data.get("fleet_failed_non_shed")
            if not isinstance(v, int) or isinstance(v, bool):
                bad("autotune artifact missing int "
                    "'fleet_failed_non_shed' summed over every replay")
        if str(data.get("metric", "")).startswith("serve_train"):
            # the r20 online-learning generation (BENCH_r20): an
            # online-loop claim is only evidence with the held-out
            # error TRAJECTORY (one point per published version — did
            # the stream actually teach the model?), the zero-drop
            # counter summed over every round, and the publish /
            # rollback ledger
            traj = data.get("serve_train_error_trajectory")
            if (not isinstance(traj, list) or not traj
                    or not all(isinstance(x, (int, float))
                               and not isinstance(x, bool)
                               for x in traj)):
                bad("serve_train artifact missing "
                    "'serve_train_error_trajectory' (non-empty list "
                    "of held-out error numbers, one per published "
                    "version — the learning evidence)")
            v = data.get("fleet_failed_non_shed")
            if not isinstance(v, int) or isinstance(v, bool):
                bad("serve_train artifact missing int "
                    "'fleet_failed_non_shed' summed over every round "
                    "(the fleet-stayed-up-through-the-swaps evidence)")
            for k in ("publishes_total", "rollbacks_total"):
                v = data.get(k)
                if not isinstance(v, int) or isinstance(v, bool):
                    bad(f"serve_train artifact missing int {k!r} (the "
                        "publish/rollback ledger)")
        if str(data.get("metric", "")).startswith("overlap"):
            # the r18 FSDP-overlap generation (BENCH_r18): the overlap
            # claim is only evidence with BOTH step-time sides AND the
            # exposed-collective split — the structural number a 1-core
            # CPU certifies even when its step-time ratio is
            # dispatch-bound
            for k in ("overlap_on_steps_per_sec",
                      "overlap_off_steps_per_sec",
                      "exposed_comm_frac_overlap_on",
                      "exposed_comm_frac_overlap_off"):
                v = data.get(k)
                if not isinstance(v, (int, float)) or isinstance(v, bool):
                    bad(f"overlap artifact missing numeric {k!r} "
                        "(the A/B sides + exposed-comm evidence)")
            for k in ("exposed_collectives_overlap_on",
                      "exposed_collectives_overlap_off"):
                v = data.get(k)
                if not isinstance(v, int) or isinstance(v, bool):
                    bad(f"overlap artifact missing int {k!r} (the "
                        "exposed-collective count per side)")
        for key, val in data.items():
            if "_vs_" not in key:
                continue
            if val is None:
                continue  # a skipped side is recorded as null
            if not isinstance(val, (int, float)) or isinstance(
                    val, bool):
                bad(f"ratio key {key!r} must be a number or null, got "
                    f"{type(val).__name__}")
                continue
            # per-metric best-of structure: an A/B ratio needs both
            # sides present so the best-of evidence is re-checkable
            stem, _, b_side = key.partition("_vs_")
            sides = [k for k in data
                     if k != key and isinstance(
                         data[k], (int, float))
                     and (k.startswith(stem.rsplit("_", 1)[0])
                          or b_side.split("_")[0] in k)]
            if len(sides) < 2:
                bad(f"A/B ratio {key!r} lacks its two sides in the "
                    "artifact (per-metric best-of structure)")
    elif "parsed" in data or "round" in data:
        if not isinstance(data.get("cmd"), (str, list)):
            bad("watcher-style artifact missing 'cmd'")
        if "parsed" in data and not isinstance(data["parsed"],
                                               (dict, type(None))):
            bad("'parsed' must be an object")
    elif "n" in data and "cmd" in data:
        if "rc" in data and not isinstance(data["rc"], int):
            bad("'rc' must be an int")
    else:
        bad("unrecognized bench artifact shape: expected metric-style "
            "('metric'+'platform'), watcher-style ('parsed'/'round'), "
            "or harness-style ('n'+'cmd') keys")
    for npath, val in _walk_numbers(data):
        if math.isnan(val) or math.isinf(val):
            bad(f"non-finite number at {npath} (a crashed averaging "
                "step wrote NaN/Infinity)")
    return findings


def run_schema_check(root: str,
                     patterns: Sequence[str] = ("BENCH_*.json",
                                                "MULTICHIP_*.json",
                                                "ACCURACY_*.json",
                                                "MEM_*.json",
                                                "TRACE_*.json",
                                                "HEALTH_*.json",
                                                "WORKLOAD_*.json")
                     ) -> List[Finding]:
    findings: List[Finding] = []
    for pattern in patterns:
        for path in sorted(glob.glob(os.path.join(root, pattern))):
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            findings.extend(check_bench_file(path, rel))
    return findings
