"""``BENCH_*.json`` artifact schema check (PT401).

Bench artifacts are the perf evidence trail (one JSON object per line /
file, per-metric best-of structure, CLAUDE.md's interleaved best-of-R
discipline). A malformed artifact — truncated JSON, a NaN ratio, an
A/B metric missing its sides — should fail at *lint* time, not at
ROADMAP-review time when the run that produced it is long gone.

Recognized shapes (all are real generations of bench output in this
repo):

- **metric style** (r07+, also BENCH_LIVE): ``{"metric": str,
  "platform": str, ...}``; every ``*_vs_*`` ratio key must be a finite
  number (or null when a side was skipped), and both sides of an A/B
  must be present when the ratio is.
- **harness style** (r01–r05): ``{"n": ..., "cmd": str, "rc": int,
  ...}``.
- **watcher style** (r06): ``{"round": ..., "cmd": ..., "parsed":
  dict, ...}``.

Everything must parse as one JSON object with finite numbers
throughout (NaN/Infinity are emitted by a crashed averaging step and
json.dumps happily writes them).
"""

from __future__ import annotations

import glob
import json
import math
import os
from typing import Any, List, Optional, Sequence

from paddle_tpu.analysis.findings import Finding


def _walk_numbers(obj: Any, path: str = "$"):
    if isinstance(obj, dict):
        for k, v in obj.items():
            yield from _walk_numbers(v, f"{path}.{k}")
    elif isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            yield from _walk_numbers(v, f"{path}[{i}]")
    elif isinstance(obj, float):
        yield path, obj


def check_bench_file(path: str, rel: str) -> List[Finding]:
    findings: List[Finding] = []

    def bad(msg: str):
        findings.append(Finding("PT401", rel, 1, msg))

    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        bad(f"unparseable bench artifact: {e}")
        return findings
    if not isinstance(data, dict):
        bad(f"bench artifact must be one JSON object, got "
            f"{type(data).__name__}")
        return findings
    # shape identification
    if "metric" in data:
        if not (isinstance(data["metric"], str) and data["metric"]):
            bad("'metric' must be a non-empty string")
        if not isinstance(data.get("platform"), str):
            bad("metric-style artifact missing 'platform'")
        for key, val in data.items():
            if "_vs_" not in key:
                continue
            if val is None:
                continue  # a skipped side is recorded as null
            if not isinstance(val, (int, float)) or isinstance(
                    val, bool):
                bad(f"ratio key {key!r} must be a number or null, got "
                    f"{type(val).__name__}")
                continue
            # per-metric best-of structure: an A/B ratio needs both
            # sides present so the best-of evidence is re-checkable
            stem, _, b_side = key.partition("_vs_")
            sides = [k for k in data
                     if k != key and isinstance(
                         data[k], (int, float))
                     and (k.startswith(stem.rsplit("_", 1)[0])
                          or b_side.split("_")[0] in k)]
            if len(sides) < 2:
                bad(f"A/B ratio {key!r} lacks its two sides in the "
                    "artifact (per-metric best-of structure)")
    elif "parsed" in data or "round" in data:
        if not isinstance(data.get("cmd"), (str, list)):
            bad("watcher-style artifact missing 'cmd'")
        if "parsed" in data and not isinstance(data["parsed"],
                                               (dict, type(None))):
            bad("'parsed' must be an object")
    elif "n" in data and "cmd" in data:
        if "rc" in data and not isinstance(data["rc"], int):
            bad("'rc' must be an int")
    else:
        bad("unrecognized bench artifact shape: expected metric-style "
            "('metric'+'platform'), watcher-style ('parsed'/'round'), "
            "or harness-style ('n'+'cmd') keys")
    for npath, val in _walk_numbers(data):
        if math.isnan(val) or math.isinf(val):
            bad(f"non-finite number at {npath} (a crashed averaging "
                "step wrote NaN/Infinity)")
    return findings


def run_schema_check(root: str,
                     patterns: Sequence[str] = ("BENCH_*.json",)
                     ) -> List[Finding]:
    findings: List[Finding] = []
    for pattern in patterns:
        for path in sorted(glob.glob(os.path.join(root, pattern))):
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            findings.extend(check_bench_file(path, rel))
    return findings
