"""Finding model + rule catalog for the graftlint static analyzer.

Every rule encodes one hard-won repo invariant (the incident that earned
it is recorded in ``docs/static_analysis.md``). Rule ids are stable —
suppressions and the baseline reference them — and grouped by pass:

- ``PT1xx`` — Pass 1, AST invariant lints (pure source analysis).
- ``PT2xx`` — Pass 2, trace-time jaxpr/lowering audits.
- ``PT3xx`` — Pass 3, lock-order analysis (static graph + runtime
  tracker ``paddle_tpu/testing/lockcheck.py``).
- ``PT4xx`` — artifact schema checks (``BENCH_*``/``MULTICHIP_*``/
  ``ACCURACY_*.json``).
- ``PT5xx`` — Pass 4, sharding & collective-communication audit of the
  real parallel programs on the 8-device virtual mesh
  (``shard_audit.py``; budget in ``comm_budget.toml``).
- ``PT6xx`` — Pass 5, per-device memory-footprint audit of the same
  compiled programs (``mem_audit.py``; budget in ``mem_budget.toml``).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

# rule id -> (short-name, one-line description)
RULES: Dict[str, Tuple[str, str]] = {
    "PT100": (
        "unparseable-source",
        "a scanned source file failed to parse — no rule can check "
        "what the AST pass cannot read (never baseline this; fix the "
        "file)"),
    "PT101": (
        "jit-closure-capture",
        "jitted function closure-captures an array-like binding; XLA "
        "embeds closure captures as program constants (the measured "
        "~4x/step deopt) — pass arrays as traced arguments"),
    "PT102": (
        "mask-bf16-cast",
        "mask tensor cast to bfloat16/float16; masks are f32 COUNT data "
        "(bf16 saturates at 256) and must never be down-cast"),
    "PT103": (
        "pad-in-bitexact-pack",
        "jnp.pad inside a bit-exact pack path; a pad fused into "
        "downstream elementwise math rounds real elements differently "
        "on XLA:CPU — pack with concatenate/slices"),
    "PT104": (
        "unguarded-jit",
        "persistent jax.jit in a hot-path module with no RecompileGuard "
        "registration and no documented cache policy — silent recompile "
        "thrash stays silent"),
    "PT105": (
        "broad-pkill",
        "broad `pkill -f` pattern in tools; pkill -f matches your own "
        "shell's command string (exit 144 self-kill)"),
    "PT106": (
        "layer-grad-matrix-row",
        "registered layer type missing its row in "
        "tests/test_layer_grad_matrix.py (static twin of "
        "test_registry_fully_covered)"),
    "PT107": (
        "chaos-site-flight-coverage",
        "a chaos hook site is not closed over by the observability "
        "plane: a _chaos._ACTIVE.hit(...) call names a site missing "
        "from chaos.SITES, a declared site has no firing row in "
        "tests/test_obs_flight.py:SITE_CASES (the closure-enforced "
        "flight-recorder matrix), or a declared site is dead — a new "
        "chaos site cannot ship without its postmortem event"),
    "PT201": (
        "jaxpr-embedded-constant",
        "traced program embeds a model-sized constant (closure-captured "
        "device array became an XLA constant)"),
    "PT202": (
        "jaxpr-donation",
        "a donatable input buffer is not donated/aliased in the lowered "
        "program"),
    "PT203": (
        "jaxpr-mask-dtype",
        "a mask input is converted below float32 inside the traced "
        "program"),
    "PT301": (
        "lock-order-inversion",
        "two locks are acquired in inconsistent order on different "
        "paths (deadlock window)"),
    "PT302": (
        "lock-self-deadlock",
        "a non-reentrant lock can be re-acquired while already held on "
        "the same call path"),
    "PT401": (
        "bench-schema",
        "evidence artifact (BENCH_*/MULTICHIP_*/ACCURACY_*/MEM_*/"
        "TRACE_*.json) violates its schema (keys, per-metric best-of "
        "structure, finite numbers; TRACE files need non-empty spans, "
        "monotone timestamps, resolvable parent refs)"),
    "PT501": (
        "collective-budget",
        "a traced parallel program's collective footprint (op sites / "
        "byte volume per mesh axis) drifted from the committed "
        "analysis/comm_budget.toml manifest — communication grew "
        "unjustified, or a win was left unpinned (the budget only "
        "shrinks)"),
    "PT502": (
        "unintended-replication",
        "a large parameter/optimizer slot a program's contract says is "
        "sharded is placed fully replicated despite a matching mesh "
        "axis — every device pays its full bytes"),
    "PT503": (
        "unpinned-shard-map-pack",
        "a packed (concatenate/pad) buffer enters a shard_map's "
        "sharded in_spec with no with_sharding_constraint pin; "
        "propagation can rewrite the producing backward (the r07 2x "
        "regression)"),
    "PT504": (
        "reshard-copy",
        "the same value chain is pinned to two different shardings in "
        "one program — each transition is a reshard copy"),
    "PT505": (
        "dead-shard-rule",
        "a rule_for table key is dead (matches no parameter), an "
        "=-exact key that exact-matches nothing, or is fully shadowed "
        "by an earlier key"),
    "PT601": (
        "mem-budget",
        "a traced program's per-device memory manifest (argument/"
        "output/temp/alias bytes + the params/opt-slots/activations "
        "role breakdown) drifted from the committed "
        "analysis/mem_budget.toml pin — footprint grew unjustified, a "
        "win was left unpinned (the budget only shrinks), or a traced "
        "program has no pin at all"),
    "PT602": (
        "sharding-efficiency-law",
        "a program's declared per-role scaling law is violated: bytes "
        "per device exceed global-bytes/N for the mesh axis the "
        "program promises to shard over (zero1 slots ~1/N over data, "
        "pipeline stacked body ~1/S over pipe, TP tables ~1/M over "
        "model)"),
    "PT603": (
        "donation-dishonesty",
        "a donated leaf the jaxpr audit (PT202) records as aliasable "
        "does not reach the compiled executable's input_output_alias/"
        "buffer_donor set, or aliasing shrinks nothing "
        "(alias bytes = 0) — the annotation is carried but the "
        "argument+temp footprint never shrinks"),
    "PT604": (
        "temp-blowup",
        "a single temp buffer in the compiled program is larger than "
        "the program's total per-device param bytes (and past the "
        "64 KiB scaffolding floor) — the full-gather-materialization "
        "smell an FSDP refactor must not regress into"),
    "PT605": (
        "mem-static-runtime-mismatch",
        "the compiled manifest's per-role bytes/device disagree with "
        "utils/profiler.memory_stats on the same params/opt_state/"
        "activations — the static audit and the runtime accounting "
        "must enforce ONE invariant from both sides"),
}

# name -> id (suppression comments may use either spelling)
RULE_BY_NAME = {name: rid for rid, (name, _) in RULES.items()}


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str          # "PT101"
    path: str          # repo-relative path
    line: int
    message: str

    @property
    def name(self) -> str:
        # tolerant of unknown ids (e.g. a typo'd baseline entry being
        # REPORTED as stale) — the report must never crash on the path
        # whose job is telling the operator what to fix
        return RULES.get(self.rule, (self.rule, ""))[0]

    def __str__(self) -> str:
        return (f"{self.path}:{self.line}: {self.rule}"
                f"({self.name}): {self.message}")


def rule_counts(findings: List[Finding]) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return dict(sorted(counts.items()))


def format_report(findings: List[Finding],
                  header: Optional[str] = None) -> str:
    lines = []
    if header:
        lines.append(header)
    for f in findings:
        lines.append(str(f))
    if findings:
        lines.append("")
        lines.append("rule counts: " + ", ".join(
            f"{rid}({RULES.get(rid, (rid, ''))[0]})={n}"
            for rid, n in rule_counts(findings).items()))
    return "\n".join(lines)
