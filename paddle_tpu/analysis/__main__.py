"""CLI: ``python -m paddle_tpu.analysis`` — run the four graftlint
passes (plus the artifact schema check) over the repo.

Exit status 0 = clean; 1 = findings; 2 = analysis itself failed.
``tools/lint.py`` is the thin CI wrapper over this module.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List

from paddle_tpu.analysis.baseline import apply_baseline, load_baseline
from paddle_tpu.analysis.findings import (RULE_BY_NAME, RULES, Finding,
                                          format_report, rule_counts)


from paddle_tpu.analysis._astutil import repo_root


def print_budget_tables(emit, as_json: bool = False) -> int:
    """``--budgets``: compile the traced programs once and print
    current-vs-pinned for both ratchet files. Strictly read-only — a
    drifted row is shown with a ``!`` marker, but updating a budget
    stays a deliberate manual edit (and the lint, not this report,
    enforces it). With ``--json``, the same data goes to stdout as the
    one JSON object the mode promises (tables to stderr via emit)."""
    from paddle_tpu.analysis import mem_audit, shard_audit
    programs = shard_audit.compile_programs(log=emit)
    comm = {e.key(): e for e in shard_audit.load_budget()}
    comm_rows = []
    seen = set()
    for cp in programs:
        manifest = shard_audit.collect_manifest(cp.hlo, cp.spec.mesh)
        for (op, axis), (n, nbytes) in sorted(manifest.items()):
            e = comm.get((cp.spec.name, op, axis))
            seen.add((cp.spec.name, op, axis))
            comm_rows.append({
                "program": cp.spec.name, "op": op, "axis": axis,
                "current": {"ops": n, "bytes": nbytes},
                "pinned": ({"ops": e.ops, "bytes": e.bytes}
                           if e else None)})
    for key in sorted(set(comm) - seen):
        e = comm[key]
        comm_rows.append({
            "program": key[0], "op": key[1], "axis": key[2],
            "current": None,
            "pinned": {"ops": e.ops, "bytes": e.bytes}})
    mem = {e.program: e for e in mem_audit.load_mem_budget()}
    mem_rows = []
    for cp in programs:
        manifest = mem_audit.memory_manifest(cp)
        e = mem.get(cp.spec.name)
        for f in mem_audit.MANIFEST_FIELDS:
            mem_rows.append({
                "program": cp.spec.name, "field": f,
                "current": manifest[f],
                "pinned": getattr(e, f) if e else None})
    for name in sorted(set(mem) - {cp.spec.name for cp in programs}):
        mem_rows.append({"program": name, "field": "(stale entry)",
                         "current": None,
                         "pinned": mem[name].arg_bytes})

    emit("\ncomm_budget.toml (pass 4) — current vs pinned:")
    emit(f"  {'program':<14}{'op':<20}{'axis':<12}"
         f"{'current':>16}{'pinned':>16}")
    for r in comm_rows:
        cur = (f"{r['current']['ops']}x/{r['current']['bytes']}B"
               if r["current"] else "(absent)")
        pin = (f"{r['pinned']['ops']}x/{r['pinned']['bytes']}B"
               if r["pinned"] else "UNPINNED")
        mark = " " if r["current"] == r["pinned"] else "!"
        emit(f" {mark}{r['program']:<14}{r['op']:<20}{r['axis']:<12}"
             f"{cur:>16}{pin:>16}")
    emit("\nmem_budget.toml (pass 5) — current vs pinned, "
         "bytes/device:")
    emit(f"  {'program':<14}{'field':<16}{'current':>12}{'pinned':>12}")
    for r in mem_rows:
        cur = r["current"] if r["current"] is not None else "(absent)"
        pin = r["pinned"] if r["pinned"] is not None else "UNPINNED"
        mark = " " if r["current"] == r["pinned"] else "!"
        emit(f" {mark}{r['program']:<14}{r['field']:<16}{cur:>12}"
             f"{pin:>12}")
    emit("\nread-only report: the ratchet is enforced by the lint "
         "passes, and budget edits stay deliberate")
    if as_json:
        print(json.dumps({"comm_budget": comm_rows,
                          "mem_budget": mem_rows}, indent=1))
    return 0


def run(argv: List[str] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="graftlint: framework-aware static analysis "
                    "(AST invariant lints, jaxpr/donation audits, "
                    "lock-order checker, sharding/collective audit, "
                    "artifact schema)")
    ap.add_argument("--root", default=repo_root())
    ap.add_argument("--skip-ast", action="store_true")
    ap.add_argument("--skip-jaxpr", action="store_true",
                    help="skip the trace-time audits (the slow pass)")
    ap.add_argument("--skip-locks", action="store_true")
    ap.add_argument("--skip-schema", action="store_true")
    ap.add_argument("--skip-shard", action="store_true",
                    help="skip pass 4 (sharding/collective audit of "
                         "the parallel programs; the slowest pass — "
                         "it compiles on the 8-device virtual mesh)")
    ap.add_argument("--skip-mem", action="store_true",
                    help="skip pass 5 (per-device memory-footprint "
                         "audit; reuses pass 4's compiles, so it is "
                         "cheap when pass 4 runs and compile-heavy "
                         "alone)")
    ap.add_argument("--budgets", action="store_true",
                    help="READ-ONLY: compile the traced programs and "
                         "print both budgets' current-vs-pinned "
                         "tables (comm_budget.toml + mem_budget.toml)"
                         ", then exit 0; regenerating a budget stays "
                         "a deliberate manual edit (ratchet policy)")
    ap.add_argument("--no-entry", action="store_true",
                    help="jaxpr pass without the flagship "
                         "__graft_entry__ build (~20s on 1 core)")
    ap.add_argument("--describe-locks", action="store_true",
                    help="print the lock graph even when clean")
    ap.add_argument("--baseline", default=None,
                    help="baseline.toml path (default: the package's)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output: one JSON object on "
                         "stdout (findings + counts); progress goes "
                         "to stderr")
    args = ap.parse_args(argv)

    # with --json, stdout is the machine contract — progress narration
    # moves to stderr so `python -m paddle_tpu.analysis --json | jq .`
    # always parses, INCLUDING the exit-2 paths (an audit crash still
    # hands the JSON consumer the findings collected before it)
    if args.json:
        def emit(*a, **k):
            print(*a, file=sys.stderr, **k)
    else:
        emit = print

    def finding_dicts(fs):
        return [{"rule": f.rule, "name": f.name, "file": f.path,
                 "line": f.line, "message": f.message} for f in fs]

    def fail_json(error: str, collected) -> int:
        if args.json:
            print(json.dumps({
                "error": error,
                "findings": finding_dicts(collected),
                "counts": rule_counts(collected),
            }, indent=1))
        return 2

    findings: List[Finding] = []
    inline_suppressed = 0
    # rule bands whose pass actually ran — stale-baseline detection is
    # scoped to these, or a baselined PT2xx entry would read as STALE
    # under --skip-jaxpr and the fast/full paths could never both pass
    ran_prefixes: List[str] = []
    t0 = time.time()
    pass4_dt = None
    pass5_dt = None
    mem_manifests = None
    # pass 4 and pass 5 audit the SAME compiled executables — whichever
    # runs first pays the compile, the other reuses it
    programs = None

    if args.budgets or not (args.skip_jaxpr and args.skip_shard
                            and args.skip_mem):
        # force the CPU platform BEFORE any jax import: the audits
        # trace real programs, and on the TPU host a wedged axon
        # tunnel would otherwise hang the lint for hours (CLAUDE.md).
        # Pass 4 additionally needs the 8-device virtual mesh.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        try:
            import jax

            jax.config.update("jax_platforms", "cpu")
        except Exception:  # noqa: BLE001 — pass 2/4/5 will surface it
            pass

    if args.budgets:
        return print_budget_tables(emit, as_json=args.json)

    if not args.skip_ast:
        from paddle_tpu.analysis.ast_lints import run_pass1
        fs, sup = run_pass1(args.root)
        emit(f"[pass 1] AST invariant lints: {len(fs)} findings "
             f"({sup} inline-suppressed)")
        findings.extend(fs)
        inline_suppressed += sup
        ran_prefixes.append("PT1")

    if not args.skip_locks:
        from paddle_tpu.analysis.lockorder import run_pass3
        fs, checker = run_pass3(args.root)
        emit(f"[pass 3] lock-order: {len(checker.locks)} locks, "
             f"{len(checker.edges)} order edges, {len(fs)} findings")
        if args.describe_locks:
            emit(checker.describe())
        findings.extend(fs)
        ran_prefixes.append("PT3")

    if not args.skip_schema:
        from paddle_tpu.analysis.bench_schema import run_schema_check
        fs = run_schema_check(args.root)
        emit(f"[schema] BENCH/MULTICHIP/ACCURACY artifacts: "
             f"{len(fs)} findings")
        findings.extend(fs)
        ran_prefixes.append("PT4")

    if not args.skip_jaxpr:
        from paddle_tpu.analysis.jaxpr_audit import run_pass2
        emit("[pass 2] jaxpr/lowering audits:")
        try:
            fs = run_pass2(args.root, log=emit,
                           include_entry=not args.no_entry)
        except Exception as e:  # noqa: BLE001 — surfaced as exit 2
            emit(f"[pass 2] AUDIT FAILED to run: {e!r}")
            if findings:
                # the crash must not bury what the other passes found
                emit(format_report(
                    findings, "findings collected before the crash:"))
            return fail_json(f"pass 2 audit failed to run: {e!r}",
                             findings)
        emit(f"[pass 2] {len(fs)} findings")
        findings.extend(fs)
        ran_prefixes.append("PT2")

    if not args.skip_shard:
        from paddle_tpu.analysis.shard_audit import (compile_programs,
                                                     run_pass4)
        emit("[pass 4] sharding/collective audit (8-device virtual "
             "mesh):")
        t4 = time.time()
        try:
            programs = compile_programs()
            fs = run_pass4(args.root, log=emit, programs=programs)
        except Exception as e:  # noqa: BLE001 — surfaced as exit 2
            emit(f"[pass 4] AUDIT FAILED to run: {e!r}")
            if findings:
                emit(format_report(
                    findings, "findings collected before the crash:"))
            return fail_json(f"pass 4 audit failed to run: {e!r}",
                             findings)
        pass4_dt = time.time() - t4
        emit(f"[pass 4] {len(fs)} findings ({pass4_dt:.1f}s)")
        findings.extend(fs)
        ran_prefixes.append("PT5")

    if not args.skip_mem:
        from paddle_tpu.analysis.mem_audit import run_pass5
        emit("[pass 5] per-device memory-footprint audit"
             + (" (reusing pass 4's compiles):" if programs is not None
                else " (compiling the traced programs):"))
        t5 = time.time()
        try:
            if programs is None:
                from paddle_tpu.analysis.shard_audit import \
                    compile_programs
                programs = compile_programs()
            fs, mem_manifests = run_pass5(args.root, log=emit,
                                          programs=programs)
        except Exception as e:  # noqa: BLE001 — surfaced as exit 2
            emit(f"[pass 5] AUDIT FAILED to run: {e!r}")
            if findings:
                emit(format_report(
                    findings, "findings collected before the crash:"))
            return fail_json(f"pass 5 audit failed to run: {e!r}",
                             findings)
        pass5_dt = time.time() - t5
        emit(f"[pass 5] {len(fs)} findings ({pass5_dt:.1f}s)")
        findings.extend(fs)
        ran_prefixes.append("PT6")

    try:
        entries = load_baseline(args.baseline)
    except ValueError as e:
        emit(f"baseline error: {e}")
        return fail_json(f"baseline error: {e}", findings)
    findings, baselined, stale = apply_baseline(findings, entries)
    from paddle_tpu.analysis.baseline import default_baseline_path
    baseline_rel = os.path.relpath(
        args.baseline or default_baseline_path(), args.root)
    for e in stale:
        rid = RULE_BY_NAME.get(e.rule, e.rule)
        if rid in RULES and not any(rid.startswith(p)
                                    for p in ran_prefixes):
            continue  # its pass was skipped this run — not evidence
        # unknown/typo'd rules fall through: they can never match any
        # pass's findings, so they are stale on EVERY run and must be
        # reported, or they sit in the baseline forever unexamined
        findings.append(Finding(
            rid, baseline_rel, 1,
            f"STALE baseline entry (rule={e.rule} path={e.path!r} "
            f"line={e.line}) matches nothing — delete it (the "
            "baseline only shrinks)"))

    dt = time.time() - t0
    # the pass-4/5 wall times ride the summary line so runtime creep in
    # the compile-heavy passes is visible run over run
    p4 = f", pass4 {pass4_dt:.1f}s" if pass4_dt is not None else ""
    p5 = f", pass5 {pass5_dt:.1f}s" if pass5_dt is not None else ""
    emit(f"\ngraftlint: {len(findings)} findings, "
         f"{baselined} baselined, {inline_suppressed} "
         f"inline-suppressed ({dt:.1f}s{p4}{p5})")
    if args.json:
        print(json.dumps({
            "findings": finding_dicts(findings),
            "counts": rule_counts(findings),
            "baselined": baselined,
            "inline_suppressed": inline_suppressed,
            "elapsed_s": round(dt, 3),
            "pass4_s": (round(pass4_dt, 3)
                        if pass4_dt is not None else None),
            "pass5_s": (round(pass5_dt, 3)
                        if pass5_dt is not None else None),
            # the MEM_* snapshot family: `--json | jq .mem_manifest
            # > MEM_rNN.json` commits a per-program per-device bytes
            # trend point; PT401 schema-checks committed ones
            "mem_manifest": ({"programs": mem_manifests}
                             if mem_manifests is not None else None),
        }, indent=1))
        return 1 if findings else 0
    if findings:
        print(format_report(findings))
        return 1
    print("rule catalog: " + ", ".join(
        f"{rid}({name})" for rid, (name, _) in sorted(RULES.items())))
    return 0


if __name__ == "__main__":
    sys.exit(run())
