"""CLI: ``python -m paddle_tpu.analysis`` — run the three graftlint
passes (plus the bench-artifact schema check) over the repo.

Exit status 0 = clean; 1 = findings; 2 = analysis itself failed.
``tools/lint.py`` is the thin CI wrapper over this module.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import List

from paddle_tpu.analysis.baseline import apply_baseline, load_baseline
from paddle_tpu.analysis.findings import (RULE_BY_NAME, RULES, Finding,
                                          format_report)


from paddle_tpu.analysis._astutil import repo_root


def run(argv: List[str] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="graftlint: framework-aware static analysis "
                    "(AST invariant lints, jaxpr/donation audits, "
                    "lock-order checker, bench-artifact schema)")
    ap.add_argument("--root", default=repo_root())
    ap.add_argument("--skip-ast", action="store_true")
    ap.add_argument("--skip-jaxpr", action="store_true",
                    help="skip the trace-time audits (the slow pass)")
    ap.add_argument("--skip-locks", action="store_true")
    ap.add_argument("--skip-schema", action="store_true")
    ap.add_argument("--no-entry", action="store_true",
                    help="jaxpr pass without the flagship "
                         "__graft_entry__ build (~20s on 1 core)")
    ap.add_argument("--describe-locks", action="store_true",
                    help="print the lock graph even when clean")
    ap.add_argument("--baseline", default=None,
                    help="baseline.toml path (default: the package's)")
    args = ap.parse_args(argv)

    findings: List[Finding] = []
    inline_suppressed = 0
    # rule bands whose pass actually ran — stale-baseline detection is
    # scoped to these, or a baselined PT2xx entry would read as STALE
    # under --skip-jaxpr and the fast/full paths could never both pass
    ran_prefixes: List[str] = []
    t0 = time.time()

    if not args.skip_jaxpr:
        # force the CPU platform BEFORE any jax import: the audit
        # traces real programs, and on the TPU host a wedged axon
        # tunnel would otherwise hang the lint for hours (CLAUDE.md)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        try:
            import jax

            jax.config.update("jax_platforms", "cpu")
        except Exception:  # noqa: BLE001 — pass 2 will surface it
            pass

    if not args.skip_ast:
        from paddle_tpu.analysis.ast_lints import run_pass1
        fs, sup = run_pass1(args.root)
        print(f"[pass 1] AST invariant lints: {len(fs)} findings "
              f"({sup} inline-suppressed)")
        findings.extend(fs)
        inline_suppressed += sup
        ran_prefixes.append("PT1")

    if not args.skip_locks:
        from paddle_tpu.analysis.lockorder import run_pass3
        fs, checker = run_pass3(args.root)
        print(f"[pass 3] lock-order: {len(checker.locks)} locks, "
              f"{len(checker.edges)} order edges, {len(fs)} findings")
        if args.describe_locks:
            print(checker.describe())
        findings.extend(fs)
        ran_prefixes.append("PT3")

    if not args.skip_schema:
        from paddle_tpu.analysis.bench_schema import run_schema_check
        fs = run_schema_check(args.root)
        print(f"[schema] BENCH_*.json: {len(fs)} findings")
        findings.extend(fs)
        ran_prefixes.append("PT4")

    if not args.skip_jaxpr:
        from paddle_tpu.analysis.jaxpr_audit import run_pass2
        print("[pass 2] jaxpr/lowering audits:")
        try:
            fs = run_pass2(args.root, log=print,
                           include_entry=not args.no_entry)
        except Exception as e:  # noqa: BLE001 — surfaced as exit 2
            print(f"[pass 2] AUDIT FAILED to run: {e!r}")
            if findings:
                # the crash must not bury what the other passes found
                print(format_report(
                    findings, "findings collected before the crash:"))
            return 2
        print(f"[pass 2] {len(fs)} findings")
        findings.extend(fs)
        ran_prefixes.append("PT2")

    try:
        entries = load_baseline(args.baseline)
    except ValueError as e:
        print(f"baseline error: {e}")
        return 2
    findings, baselined, stale = apply_baseline(findings, entries)
    from paddle_tpu.analysis.baseline import default_baseline_path
    baseline_rel = os.path.relpath(
        args.baseline or default_baseline_path(), args.root)
    for e in stale:
        rid = RULE_BY_NAME.get(e.rule, e.rule)
        if rid in RULES and not any(rid.startswith(p)
                                    for p in ran_prefixes):
            continue  # its pass was skipped this run — not evidence
        # unknown/typo'd rules fall through: they can never match any
        # pass's findings, so they are stale on EVERY run and must be
        # reported, or they sit in the baseline forever unexamined
        findings.append(Finding(
            rid, baseline_rel, 1,
            f"STALE baseline entry (rule={e.rule} path={e.path!r} "
            f"line={e.line}) matches nothing — delete it (the "
            "baseline only shrinks)"))

    dt = time.time() - t0
    print(f"\ngraftlint: {len(findings)} findings, "
          f"{baselined} baselined, {inline_suppressed} "
          f"inline-suppressed ({dt:.1f}s)")
    if findings:
        print(format_report(findings))
        return 1
    print("rule catalog: " + ", ".join(
        f"{rid}({name})" for rid, (name, _) in sorted(RULES.items())))
    return 0


if __name__ == "__main__":
    sys.exit(run())
