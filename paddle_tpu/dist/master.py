"""Fault-tolerant master: elastic task dispatch with snapshot/recover.

Re-designs `go/master/service.go` for the TPU runtime. Semantics kept
one-for-one (cited by reference line):

- dataset pre-partitioned into tasks of N chunks (`service.go:106`)
- ``get_task`` dispatches todo→pending per pass (`service.go:368`)
- pending tasks carry a timeout; expiry requeues (`service.go:341-355`)
- ``task_failed`` requeues until ``failure_max`` then discards the task —
  poison-pill isolation (`service.go:313-335`)
- every queue mutation snapshots to the Store; a restarted master
  recovers and requeues pending work (`service.go:166,207`)
- ``request_save_model`` arbitration: exactly one trainer saves per
  window, so a dead "trainer 0" can't block checkpoints (`service.go:474`)

Elastic-lease extensions beyond the reference (the chaos-hardening
round; see docs/fault_tolerance.md):

- **heartbeat-renewed leases**: a trainer renews its task lease(s) and
  its own liveness with ``heartbeat``; a trainer that goes silent for
  ``trainer_timeout_s`` has its pending lease AND its uncommitted
  finishes requeued (at the *front*, preserving dispatch order).
- **idempotent finishes**: ``task_finished`` is at-least-once safe — a
  duplicate report (lost response + client retry, or a straggler's
  second copy) dedupes against the done ledger instead of failing.
- **commit protocol**: with ``defer_commit`` a finished task parks in a
  per-trainer *uncommitted* buffer until ``commit_tasks`` (sent by the
  trainer after its checkpoint is durable). Work a trainer finished
  after its last durable checkpoint is therefore requeued on its death
  instead of being marked trained-but-lost.
- **straggler re-dispatch**: when todo is dry, a pending task older than
  ``straggle_after_s`` is speculatively re-served to an idle trainer;
  the first finish wins, the duplicate dedupes.
- **exact resume**: ``resume_lease`` reconciles the queue against the
  task ledger a resumed trainer restored from its checkpoint — the
  `trainer/trainer.py` pass-aware resume fix.

etcd is replaced by a ``Store`` interface (atomic checksummed file by
default — on cloud deployments this maps naturally onto GCS); Go net/rpc
+ gob becomes length-prefixed JSON over TCP; leader election is out of
scope for a single-master-per-job setup (the Store detects torn writes).
Fault injection: the RPC codec and the snapshot path carry
``paddle_tpu.testing.chaos`` hook points (``msg_send`` / ``msg_recv`` /
``store_save``) — zero-cost unless a FaultPlan is installed.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import random
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Dict, List, Optional

from paddle_tpu.obs import flight as _flight
from paddle_tpu.obs import trace as _trace
from paddle_tpu.testing import chaos as _chaos
from paddle_tpu.utils.backoff import backoff_delay
from paddle_tpu.utils.log import get_logger

logger = get_logger("dist.master")


@dataclasses.dataclass
class Task:
    id: int
    chunks: List[Any]          # opaque chunk descriptors (paths, ranges…)
    epoch: int = 0             # pass the task was last dispatched in
    num_failures: int = 0

    def to_dict(self):
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d):
        return cls(**d)


def partition_chunks(chunks: List[Any], chunks_per_task: int) -> List[Task]:
    """Pre-partition dataset chunks into tasks (`service.go:106`)."""
    if chunks_per_task <= 0:
        raise ValueError("chunks_per_task must be positive")
    tasks = []
    for i in range(0, len(chunks), chunks_per_task):
        tasks.append(Task(id=len(tasks), chunks=chunks[i:i + chunks_per_task]))
    return tasks


class InMemStore:
    """`go/master/inmem_store.go`: single-slot store for tests."""

    def __init__(self):
        self._buf: Optional[bytes] = None
        self._lock = threading.Lock()

    def save(self, data: bytes):
        with self._lock:
            self._buf = data

    def load(self) -> Optional[bytes]:
        with self._lock:
            return self._buf


class FileStore:
    """Atomic checksummed snapshot file (the etcd replacement).

    Write = tmp file + fsync + rename; an MD5 header detects torn/corrupt
    snapshots on load (the reference trusts etcd's consistency; a file
    needs the checksum — same guard as the pserver checkpoint's
    ``WrongChecksum``, `go/pserver/service.go:49`)."""

    def __init__(self, path: str):
        self.path = path

    def save(self, data: bytes):
        tmp = self.path + ".tmp"
        digest = hashlib.md5(data).hexdigest().encode()
        with open(tmp, "wb") as f:
            f.write(digest + b"\n" + data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def load(self) -> Optional[bytes]:
        try:
            with open(self.path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return None
        digest, _, data = raw.partition(b"\n")
        if hashlib.md5(data).hexdigest().encode() != digest:
            logger.warning("snapshot checksum mismatch at %s; ignoring",
                           self.path)
            return None
        return data


# "not passed" marker for straggle_after_s: None must stay a meaningful
# value (speculative re-dispatch disabled), not an alias for the default
_AUTO_STRAGGLE = object()


class LeaseTable:
    """Heartbeat-renewed liveness ledger — the lease primitive the
    master's trainer liveness always was, extracted so the replica
    supervisor (``serving/supervisor.py``) can lease replica processes
    through the SAME machinery instead of reinventing it.

    A holder renews its lease with :meth:`renew`; :meth:`expired` pops
    and returns every holder whose last renewal is older than
    ``timeout_s``. Monotonic clock, single-process. NOT itself
    thread-safe: the owner (MasterService under its RLock, the
    supervisor under its own lock) serializes access — a second lock
    here would add a lock-order edge for no isolation gain."""

    def __init__(self, timeout_s: float):
        self.timeout_s = float(timeout_s)
        self._seen: Dict[str, float] = {}

    def renew(self, holder: Optional[str]):
        if holder is not None:
            self._seen[holder] = time.monotonic()

    def renew_all(self, holders):
        now = time.monotonic()
        for h in holders:
            self._seen[h] = now

    def drop(self, holder: str):
        self._seen.pop(holder, None)

    def expired(self, now: Optional[float] = None) -> List[str]:
        """Pop and return every holder past ``timeout_s`` — each is
        reported exactly once (the caller owns the consequence; a
        holder that renews again afterwards simply re-enters)."""
        now = time.monotonic() if now is None else now
        dead = [h for h, seen in self._seen.items()
                if now - seen > self.timeout_s]
        for h in dead:
            del self._seen[h]
        return dead

    def age(self, holder: str) -> Optional[float]:
        t = self._seen.get(holder)
        return None if t is None else time.monotonic() - t

    def holders(self) -> List[str]:
        return list(self._seen)

    def __contains__(self, holder) -> bool:
        return holder in self._seen


class RoleLease:
    """Fenced single-holder role lease over a :class:`Store` — the
    "active router" election for router HA (``serving/router.py:
    RouterHA``).

    The record is tiny JSON in the store: ``{role, holder, epoch,
    nonce, renewed_at}`` with a WALL-clock ``renewed_at`` (two processes
    cannot compare monotonic clocks). Semantics:

    - :meth:`try_acquire` takes the role when it is free, released, or
      stale (``renewed_at`` older than ``ttl_s``), bumping ``epoch`` —
      the fencing token. Last-writer-wins with a ``settle_s`` read-back
      window (the FileStore has atomic replace but no CAS; a real
      multi-host deployment backs the Store with etcd/GCS preconditions
      — the epoch fence below bounds the damage of the race either
      way).
    - :meth:`renew` re-reads first: if the record no longer names this
      holder AND epoch, the role was taken with a higher epoch — the
      renew FAILS and local validity drops, so the old holder fences
      itself within one renewal period. The chaos site ``lease_renew``
      fires here: a ``drop`` is a lost renewal (the partition fault).
    - :meth:`valid` is the lock-free fencing check the router's
      dispatch path polls: true only within ``ttl_s`` of the last
      SUCCESSFUL acquire/renew. A partitioned old active whose renewals
      stop dispatching within one ttl — the r11 epoch-guard idea
      (a zombie's stale action must not land) applied to routing.
    """

    def __init__(self, store, holder_id: str, *, ttl_s: float = 3.0,
                 role: str = "active", settle_s: float = 0.05):
        self.store = store
        self.holder_id = str(holder_id)
        self.ttl_s = float(ttl_s)
        self.role = str(role)
        self.settle_s = float(settle_s)
        self.epoch = 0
        # monotonic deadline of local validity; plain float read/write
        # (atomic in CPython) — dispatch polls this lock-free
        self._valid_until = 0.0

    # ------------------------------------------------------------ store
    def _read(self) -> Optional[dict]:
        raw = self.store.load()
        if not raw:
            return None
        try:
            rec = json.loads(raw.decode())
        except (ValueError, UnicodeDecodeError):
            return None  # torn/foreign record reads as "free"
        return rec if isinstance(rec, dict) else None

    def _write(self, rec: dict):
        self.store.save(json.dumps(rec).encode())

    def peek(self) -> Optional[dict]:
        """The current record, whoever holds it (standby's watch)."""
        return self._read()

    # ------------------------------------------------------------- role
    def valid(self) -> bool:
        return time.monotonic() < self._valid_until

    def try_acquire(self) -> bool:
        """Take the role if free/stale/ours. Returns True only after a
        read-back confirms our write survived the settle window."""
        rec = self._read()
        now = time.time()
        if (rec and rec.get("holder")
                and rec.get("holder") != self.holder_id
                and now - float(rec.get("renewed_at", 0)) <= self.ttl_s):
            return False  # live foreign holder
        # epoch only grows — even re-acquiring our own stale record
        # bumps it, so every acquisition is a fresh fencing token
        epoch = int(rec.get("epoch", 0) if rec else 0) + 1
        nonce = f"{self.holder_id}:{epoch}:{os.urandom(4).hex()}"
        self._write({"role": self.role, "holder": self.holder_id,
                     "epoch": epoch, "nonce": nonce, "renewed_at": now})
        if self.settle_s:
            time.sleep(self.settle_s)
        back = self._read()
        if (back and back.get("holder") == self.holder_id
                and back.get("nonce") == nonce):
            self.epoch = epoch
            self._valid_until = time.monotonic() + self.ttl_s
            logger.info("role %r acquired by %s (epoch %d)", self.role,
                        self.holder_id, epoch)
            if _flight._ACTIVE is not None:
                # the fencing epoch is the postmortem's ordering token:
                # "who held the role when" reads off these events
                _flight._ACTIVE.record(
                    "role_acquire", role=self.role,
                    holder=self.holder_id, epoch=epoch,
                    took_over_stale=bool(rec and rec.get("holder")
                                         and rec.get("holder")
                                         != self.holder_id))
            return True
        return False

    def renew(self) -> bool:
        """Renew while we hold the role; False (and local validity
        drops at its ttl) once a higher epoch took it. Raises
        ``ChaosDropped`` under an injected ``lease_renew`` drop — the
        caller treats that exactly like a lost renewal."""
        if _chaos._ACTIVE is not None:
            _chaos._ACTIVE.hit("lease_renew", holder=self.holder_id,
                               role=self.role)
        rec = self._read()
        if (not rec or rec.get("holder") != self.holder_id
                or int(rec.get("epoch", -1)) != self.epoch):
            # fenced: the role moved on with a higher epoch — this
            # holder must NOT keep acting on its stale validity window
            self._valid_until = 0.0
            if _flight._ACTIVE is not None:
                _flight._ACTIVE.record(
                    "role_renew_refused", role=self.role,
                    holder=self.holder_id, epoch=self.epoch,
                    record_epoch=(rec or {}).get("epoch"),
                    record_holder=(rec or {}).get("holder"))
            return False
        rec["renewed_at"] = time.time()
        self._write(rec)
        self._valid_until = time.monotonic() + self.ttl_s
        return True

    def release(self):
        """Explicit hand-back (clean shutdown): the record keeps its
        epoch (tokens only grow) but drops the holder, so a standby
        acquires without waiting out the ttl."""
        self._valid_until = 0.0
        rec = self._read()
        if (rec and rec.get("holder") == self.holder_id
                and int(rec.get("epoch", -1)) == self.epoch):
            rec["holder"] = None
            rec["renewed_at"] = 0.0
            self._write(rec)


class MasterService:
    """The task-queue state machine. Thread-safe; every mutation
    snapshots to the store."""

    def __init__(self, store=None, *, timeout_s: float = 60.0,
                 failure_max: int = 3, chunks_per_task: int = 1,
                 trainer_timeout_s: Optional[float] = None,
                 straggle_after_s: Optional[float] = _AUTO_STRAGGLE):
        self.store = store or InMemStore()
        self.timeout_s = timeout_s
        self.failure_max = failure_max
        self.chunks_per_task = chunks_per_task
        # a silent trainer's leases + uncommitted work requeue after this
        self.trainer_timeout_s = (timeout_s if trainer_timeout_s is None
                                  else trainer_timeout_s)
        # a pending task older than this is re-served speculatively when
        # todo is dry (first finish wins); default half the task timeout.
        # An explicit None DISABLES speculative re-dispatch — tasks whose
        # load_chunk has side effects must never run twice
        self.straggle_after_s = (timeout_s / 2
                                 if straggle_after_s is _AUTO_STRAGGLE
                                 else straggle_after_s)
        self._lock = threading.RLock()
        self.todo: List[Task] = []
        self.pending: Dict[int, Task] = {}
        self._deadlines: Dict[int, float] = {}
        self._dispatch_t: Dict[int, float] = {}  # straggle clock per task
        self._owner: Dict[str, int] = {}  # trainer_id -> leased task id
        self.done: List[Task] = []
        self._done_ids: set = set()
        self.done_by: Dict[int, Optional[str]] = {}
        # finished-but-uncommitted per trainer, in finish order (commit
        # protocol: these requeue if the trainer dies before committing)
        self.uncommitted: Dict[str, List[Task]] = {}
        # trainer liveness = heartbeat-renewed leases (the same
        # LeaseTable the replica supervisor leases processes through)
        self._trainer_seen = LeaseTable(self.trainer_timeout_s)
        self.failed: List[Task] = []
        self.cur_pass = 0
        self._ready = False
        # streaming mode (the r20 online loop): while a stream is open
        # the task list GROWS (``extend_dataset``) and a drained queue
        # answers "wait" instead of "end" — the pass ends only when the
        # producer closes the stream
        self._stream_open = False
        self._last_save: float = -1e30
        self._recover()

    # ------------------------------------------------------------ state

    def metrics_snapshot(self) -> dict:
        """Queue/lease counters for the ``--metrics_port`` exporter
        (metrics federation: the master scrapes like everything else).
        Counts only — task payloads stay out of the metrics plane."""
        with self._lock:
            return {
                "cur_pass": self.cur_pass,
                "ready": self._ready,
                "todo": len(self.todo),
                "pending": len(self.pending),
                "done": len(self.done),
                "failed": len(self.failed),
                "uncommitted_tasks": sum(
                    len(ts) for ts in self.uncommitted.values()),
                "uncommitted_trainers": sum(
                    1 for ts in self.uncommitted.values() if ts),
                "live_trainers": len(self._trainer_seen.holders()),
            }

    def _snapshot_bytes(self) -> bytes:
        state = {
            "todo": [t.to_dict() for t in self.todo],
            "pending": [t.to_dict() for t in self.pending.values()],
            "done": [t.to_dict() for t in self.done],
            "failed": [t.to_dict() for t in self.failed],
            "uncommitted": {tr: [t.to_dict() for t in ts]
                            for tr, ts in self.uncommitted.items() if ts},
            "done_by": {str(tid): tr for tid, tr in self.done_by.items()},
            "cur_pass": self.cur_pass,
            "ready": self._ready,
            "stream": self._stream_open,
        }
        return json.dumps(state).encode()

    def _snapshot(self):
        if _chaos._ACTIVE is not None:
            _chaos._ACTIVE.hit("store_save")
        self.store.save(self._snapshot_bytes())

    def _recover(self):
        data = self.store.load()
        if not data:
            return
        state = json.loads(data.decode())
        self.todo = [Task.from_dict(d) for d in state["todo"]]
        # work that was in flight (pending lease) when the master died →
        # requeue at the FRONT, in order (`service.go:166` region:
        # recovered state resets dispatch; the front-requeue keeps a
        # single-trainer job's dispatch order stable so exact resume
        # stays exact across a master death). A live trainer that was
        # mid-way through that very task reconciles via the idempotent
        # ``task_finished`` (which claims a requeued copy from todo).
        recovered = [Task.from_dict(d) for d in state["pending"]]
        self.todo = recovered + self.todo
        # finished-but-uncommitted work stays PARKED, not requeued: its
        # trainer may be alive mid-stream (a master-only death) and has
        # already trained it — requeueing would double-train. Its
        # liveness clock restarts NOW: if the trainer never returns to
        # commit (it died too), trainer_timeout_s expiry requeues.
        self.uncommitted = {
            tr: [Task.from_dict(d) for d in ts]
            for tr, ts in state.get("uncommitted", {}).items()}
        self._trainer_seen.renew_all(self.uncommitted)
        self.done = [Task.from_dict(d) for d in state["done"]]
        self._done_ids = {t.id for t in self.done}
        self.done_by = {int(k): v
                        for k, v in state.get("done_by", {}).items()
                        if int(k) in self._done_ids}
        self.failed = [Task.from_dict(d) for d in state["failed"]]
        self.cur_pass = state["cur_pass"]
        self._ready = state["ready"]
        self._stream_open = state.get("stream", False)
        logger.info("master recovered: %d todo (%d requeued), %d done, "
                    "%d failed, pass %d", len(self.todo), len(recovered),
                    len(self.done), len(self.failed), self.cur_pass)

    # ------------------------------------------------------------- API

    def set_dataset(self, chunks: List[Any]):
        """Idempotent: only the first caller partitions (`service.go`
        SetDataset; later trainers' calls are no-ops once ready)."""
        with self._lock:
            if self._ready:
                return
            self.todo = partition_chunks(chunks, self.chunks_per_task)
            self._ready = True
            self._snapshot()

    # -------------------------------------------------------- streaming
    # The r20 online loop's surface (in-process only — deliberately NOT
    # in RPC_METHODS: the tailer owns its master, there is no remote
    # producer). A stream is one never-rolling pass whose task list
    # grows as replay segments seal; "end" arrives only after
    # ``end_stream``.

    def open_stream(self):
        """Begin (or resume) streaming ingest: the job is ready with an
        initially-empty, growable task list. Idempotent against a
        recovered snapshot — a restarted tailer re-opens the stream it
        crashed out of without disturbing the recovered ledger."""
        with self._lock:
            self._stream_open = True
            self._ready = True
            self._snapshot()

    def extend_dataset(self, chunks: List[Any]) -> int:
        """Append newly-visible chunks to the open stream, deduplicated
        by chunk VALUE against everything this job has ever queued —
        the periodic tail scan re-reports old segments and a restarted
        scanner re-reports ALL of them, so idempotence lives here, not
        in the caller. Returns how many chunks were actually new."""
        with self._lock:
            if not self._stream_open:
                raise RuntimeError("extend_dataset on a closed stream")
            known = set()
            for bucket in (self.todo, self.pending.values(), self.done,
                           self.failed):
                for t in bucket:
                    known.update(t.chunks)
            for ts in self.uncommitted.values():
                for t in ts:
                    known.update(t.chunks)
            fresh = [c for c in chunks if c not in known]
            if not fresh:
                return 0
            next_id = 1 + max(
                (t.id for bucket in (self.todo, self.pending.values(),
                                     self.done, self.failed)
                 for t in bucket),
                default=-1)
            for ts in self.uncommitted.values():
                for t in ts:
                    next_id = max(next_id, t.id + 1)
            new_tasks = partition_chunks(fresh, self.chunks_per_task)
            for t in new_tasks:
                t.id += next_id
                t.epoch = self.cur_pass
            self.todo.extend(new_tasks)
            self._snapshot()
            return len(fresh)

    def end_stream(self):
        """Close the stream: no more ``extend_dataset`` calls are
        coming, and a drained queue may now answer "end" — the reader
        finishes its pass and the loop unwinds."""
        with self._lock:
            self._stream_open = False
            self._snapshot()

    def _release_owner(self, task_id: int):
        for trainer, tid in list(self._owner.items()):
            if tid == task_id:
                del self._owner[trainer]

    def _touch_trainer(self, trainer_id: Optional[str]):
        self._trainer_seen.renew(trainer_id)

    def _mark_done(self, task: Task, trainer_id: Optional[str]):
        task.num_failures = 0
        self.done.append(task)
        self._done_ids.add(task.id)
        self.done_by[task.id] = trainer_id

    def _check_timeouts(self):
        now = time.monotonic()
        expired = [tid for tid, dl in self._deadlines.items() if dl <= now]
        # _deadlines is insertion-ordered = dispatch-ordered; each
        # front-insert reverses, so walk the batch BACKWARDS and the
        # net prepend preserves dispatch order — a survivor re-trains
        # simultaneous expiries in the order they were first served
        for tid in reversed(expired):
            task = self.pending.pop(tid)
            del self._deadlines[tid]
            self._dispatch_t.pop(tid, None)
            self._release_owner(tid)
            self._process_failure(task, "timeout", front=True,
                                  snapshot=False)
        if expired:
            self._snapshot()
        # trainer liveness: a silent trainer's pending lease AND its
        # uncommitted finishes go back to the queue — heartbeats stopped,
        # so waiting out the (possibly much longer) task deadline would
        # delay re-dispatch past trainer_timeout_s, and requeueing the
        # lease AFTER the uncommitted finishes here would invert dispatch
        # order. Front-requeue the in-flight task first, then prepend the
        # finishes: todo = [finishes..., in-flight, ...rest].
        for tr in self._trainer_seen.expired(now):
            if _flight._ACTIVE is not None:
                # the flight ring is lock-free by design, so recording
                # under the master RLock adds no lock-order edge
                _flight._ACTIVE.record("trainer_lease_expired",
                                       trainer=tr)
            self._requeue_trainer(tr, "lease expired")

    def _requeue_trainer(self, trainer_id: str, why: str) -> int:
        """Requeue everything a trainer holds — its in-flight lease and
        its parked uncommitted finishes — preserving dispatch order:
        todo = [finishes..., in-flight, ...rest]. Shared by liveness
        expiry (a dead trainer) and the explicit ``release_lease`` (a
        live-but-unwound one); a per-task map added to one path and
        missed by the other would silently leak state or diverge the
        requeue ordering. Caller holds the lock; liveness is the
        CALLER's business (expiry drops it, release keeps it — the
        process is alive). Returns how many tasks went back."""
        n = 0
        tid = self._owner.pop(trainer_id, None)
        if tid is not None and tid in self.pending:
            task = self.pending.pop(tid)
            self._deadlines.pop(tid, None)
            self._dispatch_t.pop(tid, None)
            logger.warning(
                "trainer %s (%s): requeueing in-flight task %d",
                trainer_id, why, tid)
            self._process_failure(task, why, front=True, snapshot=False)
            n += 1
        stale = self.uncommitted.pop(trainer_id, [])
        if stale:
            logger.warning(
                "trainer %s (%s): requeueing %d uncommitted task(s) %s",
                trainer_id, why, len(stale), [t.id for t in stale])
            for t in stale:
                t.num_failures = 0
            self.todo = stale + self.todo
            n += len(stale)
        if n:
            self._snapshot()
        return n

    def _process_failure(self, task: Task, why: str, front: bool = False,
                         snapshot: bool = True):
        # `service.go:313` processFailedTask. Timeout/death requeues go
        # to the FRONT (the task returns to its place in dispatch order);
        # reported failures go to the BACK (poison-pill isolation: a bad
        # chunk must not head-of-line-block the queue while it burns
        # through failure_max). ``snapshot=False`` lets batch callers
        # (expiry sweep, trainer requeue) serialize+fsync the store ONCE
        # for the whole batch instead of per task, all under the lock.
        task.num_failures += 1
        if task.num_failures > self.failure_max:
            logger.warning("task %d discarded after %d failures (%s)",
                           task.id, task.num_failures, why)
            self.failed.append(task)
        else:
            logger.info("task %d requeued (%s, failure %d/%d)", task.id,
                        why, task.num_failures, self.failure_max)
            if front:
                self.todo.insert(0, task)
            else:
                self.todo.append(task)
        if snapshot:
            self._snapshot()

    def get_task(self, pass_id: int = 0, trainer_id: Optional[str] = None):
        """("task", task_dict) | ("wait", None) | ("end", None).

        Pass-gated like the reference's per-pass record streams
        (`service.go:368` ErrPassBefore/ErrPassAfter): a trainer asks for
        tasks of ITS pass. "end" means that pass is fully resolved; "wait"
        means tasks are in flight elsewhere (or an earlier pass is still
        draining). The roll to the next pass happens when the first
        trainer asks for a later pass after a drain. A trainer that is a
        pass ahead may be served a straggler task requeued from the
        previous pass (at-least-once repair keeps the job live when the
        task's original owner died).

        ``trainer_id`` makes the call idempotent: if the caller already
        holds an unresolved task (its previous response was lost in a
        connection drop and the client re-sent the request), that same
        task is re-served with a fresh deadline instead of leaking a
        pending lease that would time out and count a spurious failure.

        When todo is dry but a pending task has been out for more than
        ``straggle_after_s``, it is re-served to the (idle) caller — a
        speculative second copy; the first ``task_finished`` wins and
        the loser's report dedupes."""
        with self._lock:
            if not self._ready:
                return ("wait", None)
            self._touch_trainer(trainer_id)
            self._check_timeouts()
            if trainer_id is not None and trainer_id in self._owner:
                tid = self._owner[trainer_id]
                if tid in self.pending:
                    self._deadlines[tid] = time.monotonic() + self.timeout_s
                    return ("task", self.pending[tid].to_dict())
            if pass_id < self.cur_pass:
                return ("end", None)
            if not self.todo:
                if self.pending:
                    task = self._straggler_candidate(trainer_id)
                    if task is not None:
                        self._deadlines[task.id] = (time.monotonic()
                                                    + self.timeout_s)
                        # restart the straggle clock: the next idle
                        # caller should cover the next-oldest pending
                        # task, not stack more copies onto this one
                        self._dispatch_t[task.id] = time.monotonic()
                        if trainer_id is not None:
                            self._owner[trainer_id] = task.id
                        logger.info(
                            "task %d re-dispatched to %s (straggler copy)",
                            task.id, trainer_id)
                        return ("task", task.to_dict())
                    return ("wait", None)
                if pass_id == self.cur_pass:
                    # an open stream's pass never drains to "end": the
                    # tail may grow any moment — the caller polls until
                    # the producer closes the stream
                    if self._stream_open:
                        return ("wait", None)
                    return ("end", None)
                if self._stream_open:
                    # a stream is ONE pass by construction; a caller
                    # from a later pass (stale resume state) waits
                    # rather than rolling the stream's ledger
                    return ("wait", None)
                # drained and the caller is a pass ahead → roll, but
                # ONLY once every parked finish has committed. A
                # trainer's end-of-pass checkpoint may still be fsyncing
                # on its background writer (the commit arrives via
                # ``on_save`` AFTER durability) — committing here would
                # mark work durable that is not, exactly the
                # trained-but-lost hole the commit protocol closes. The
                # wait is live: a healthy owner commits (durable save,
                # or the reader's uncoupled pass-end commit) and the
                # roll proceeds; a dead owner's liveness expiry requeues
                # its parked work into THIS pass instead.
                if any(self.uncommitted.values()):
                    return ("wait", None)
                self.todo = self.done + self.failed
                for t in self.todo:
                    t.num_failures = 0
                self.done, self.failed = [], []
                self._done_ids = set()
                self.done_by = {}
                self.cur_pass += 1
                self._snapshot()
            task = self.todo.pop(0)
            task.epoch = self.cur_pass
            self.pending[task.id] = task
            self._deadlines[task.id] = time.monotonic() + self.timeout_s
            self._dispatch_t.setdefault(task.id, time.monotonic())
            if trainer_id is not None:
                self._owner[trainer_id] = task.id
            self._snapshot()
            return ("task", task.to_dict())

    def _straggler_candidate(self, trainer_id) -> Optional[Task]:
        if trainer_id is None or self.straggle_after_s is None:
            return None
        now = time.monotonic()
        oldest, oldest_t = None, None
        for tid, task in self.pending.items():
            t0 = self._dispatch_t.get(tid)
            if t0 is None or now - t0 < self.straggle_after_s:
                continue
            if self._owner.get(trainer_id) == tid:
                continue  # the caller already holds this very lease
            if oldest_t is None or t0 < oldest_t:
                oldest, oldest_t = task, t0
        return oldest

    def pass_finished(self) -> bool:
        """True when every task of the current pass is resolved
        (uncommitted finishes count as resolved — they are trained,
        merely awaiting their trainer's checkpoint commit)."""
        with self._lock:
            self._check_timeouts()
            return self._ready and not self.todo and not self.pending

    def task_finished(self, task_id: int,
                      trainer_id: Optional[str] = None,
                      defer_commit: bool = False) -> bool:
        """Idempotent, at-least-once-safe finish. True whenever the task
        is (now) resolved: first report moves it out of pending; a
        duplicate report (client retry after a lost response, or the
        losing copy of a straggler re-dispatch) finds it in the done
        ledger / uncommitted buffer and succeeds as a no-op; a report
        for a task that timed out back into todo claims it from there
        (the work WAS done — counting it failed would retrain it).
        False only for ids this job has never known unresolved."""
        with self._lock:
            self._touch_trainer(trainer_id)
            task = self.pending.pop(task_id, None)
            self._deadlines.pop(task_id, None)
            self._dispatch_t.pop(task_id, None)
            self._release_owner(task_id)
            if task is None:
                if task_id in self._done_ids:
                    return True  # duplicate of a committed finish
                for ts in self.uncommitted.values():
                    if any(t.id == task_id for t in ts):
                        return True  # duplicate of an uncommitted finish
                for i, t in enumerate(self.todo):
                    # finished after a timeout/death requeue WITHIN this
                    # pass (epoch = last dispatch pass). A recycled copy
                    # in a LATER pass keeps its stale epoch until
                    # re-dispatched — a delayed duplicate finish from the
                    # previous pass must not mark the new pass's copy
                    # trained.
                    if t.id == task_id and t.epoch == self.cur_pass:
                        task = self.todo.pop(i)
                        break
                if task is None:
                    return False
            if defer_commit and trainer_id is not None:
                task.num_failures = 0
                self.uncommitted.setdefault(trainer_id, []).append(task)
            else:
                self._mark_done(task, trainer_id)
            self._snapshot()
            return True

    def task_failed(self, task_id: int) -> bool:
        with self._lock:
            task = self.pending.pop(task_id, None)
            self._deadlines.pop(task_id, None)
            self._dispatch_t.pop(task_id, None)
            self._release_owner(task_id)
            if task is None:
                return False
            self._process_failure(task, "reported")
            return True

    def commit_tasks(self, trainer_id: str,
                     task_ids: Optional[List[int]] = None) -> int:
        """Move this trainer's uncommitted finishes to the durable done
        ledger — sent after the trainer's checkpoint containing that
        work is durable. ``task_ids=None`` commits everything buffered.
        Idempotent; returns how many tasks moved."""
        with self._lock:
            self._touch_trainer(trainer_id)
            buf = self.uncommitted.get(trainer_id, [])
            if task_ids is None:
                take, keep = buf, []
            else:
                want = {int(i) for i in task_ids}
                take = [t for t in buf if t.id in want]
                keep = [t for t in buf if t.id not in want]
            if not take:
                return 0
            self.uncommitted[trainer_id] = keep
            for t in take:
                if t.id not in self._done_ids:
                    self._mark_done(t, trainer_id)
            self._snapshot()
            return len(take)

    def heartbeat(self, trainer_id: str) -> bool:
        """Renew the trainer's liveness and the deadline of every task
        it holds (`etcd lease keepalive` role)."""
        with self._lock:
            self._touch_trainer(trainer_id)
            tid = self._owner.get(trainer_id)
            if tid is not None and tid in self._deadlines:
                self._deadlines[tid] = time.monotonic() + self.timeout_s
            return True

    def current_pass(self) -> int:
        with self._lock:
            return self.cur_pass

    def resume_lease(self, trainer_id: str, pass_id: int,
                     done_ids: List[int],
                     inflight_id: Optional[int] = None,
                     prev_trainer_id: Optional[str] = None) -> dict:
        """Reconcile the queue against the task ledger a resumed trainer
        restored from its checkpoint (the real fix for the pass-aware
        mid-pass resume caveat):

        - every task the checkpoint recorded as consumed (``done_ids``)
          is marked done, wherever it currently sits (requeued by a
          lease expiry, parked uncommitted, still pending under the
          trainer's stale lease);
        - every task THIS trainer finished *beyond* the checkpoint
          (uncommitted, or committed from a newer-but-lost generation)
          is requeued — the restored parameters do not contain that
          training;
        - the checkpoint's in-flight task (``inflight_id``) moves to the
          queue front so the resumed reader re-acquires it first and
          can skip its already-trained record prefix;
        - the requeued slice is re-sorted by task id and the in-flight
          task fronted, so a single-trainer job replays the exact
          dispatch order of the uninterrupted run; the REST of the
          queue keeps its order (front-requeues, poison-pill backs).

        ``prev_trainer_id`` is the id the checkpoint's ledger was
        written under (the previous life of this trainer — the default
        id is pid-derived and NOT stable across restarts): its parked
        finishes, done-ledger entries, and stale lease are reconciled
        as this trainer's own, so work the old life committed from a
        newer-but-LOST checkpoint generation is requeued instead of
        staying marked trained in parameters that no longer contain it.

        No-op (returns the authoritative pass) when the master has
        already moved past ``pass_id``."""
        with self._lock:
            self._check_timeouts()
            self._touch_trainer(trainer_id)
            if pass_id != self.cur_pass:
                return {"pass": self.cur_pass, "requeued": 0, "done": 0}
            done_set = {int(i) for i in done_ids}
            moved = requeued = 0
            # (a) checkpoint-consumed tasks → done, from wherever —
            # including finishes parked under a PREVIOUS life's trainer
            # id (the default id is pid-derived, not stable across
            # restarts): leaving them parked would hold the
            # durability-gated pass roll until lease expiry and then
            # retrain work the checkpoint already proved durable
            for src in [self.todo] + list(self.uncommitted.values()):
                for t in [t for t in src if t.id in done_set]:
                    src.remove(t)
                    if t.id not in self._done_ids:
                        self._mark_done(t, trainer_id)
                        moved += 1
            for tid in [tid for tid in list(self.pending)
                        if tid in done_set]:
                t = self.pending.pop(tid)
                self._deadlines.pop(tid, None)
                self._dispatch_t.pop(tid, None)
                self._release_owner(tid)
                if t.id not in self._done_ids:
                    self._mark_done(t, trainer_id)
                    moved += 1
            # (b) this trainer's post-checkpoint work → back to todo;
            # "this trainer" spans its previous life's id too
            selves = {trainer_id}
            if prev_trainer_id:
                selves.add(prev_trainer_id)
                if prev_trainer_id != trainer_id:
                    # the old process is gone; don't let its liveness
                    # entry linger until the timeout fires spuriously
                    self._trainer_seen.drop(prev_trainer_id)
            back: List[Task] = []
            for self_id in selves:
                for t in self.uncommitted.pop(self_id, []):
                    if t.id not in done_set:
                        back.append(t)
            for t in [t for t in self.done
                      if self.done_by.get(t.id) in selves
                      and t.id not in done_set]:
                self.done.remove(t)
                self._done_ids.discard(t.id)
                self.done_by.pop(t.id, None)
                back.append(t)
            # (c) its stale pending lease(s) are void — the process is
            # gone (old id) or re-acquiring from scratch (new id)
            for self_id in selves:
                stale_tid = self._owner.pop(self_id, None)
                if stale_tid is not None and stale_tid in self.pending \
                        and stale_tid not in done_set:
                    back.append(self.pending.pop(stale_tid))
                    self._deadlines.pop(stale_tid, None)
                    self._dispatch_t.pop(stale_tid, None)
            for t in back:
                t.num_failures = 0
            requeued = len(back)
            # (d) deterministic replay order for the REQUEUED slice only
            # (a single-trainer job dispatches in id order, so its
            # resumed prefix must too); the rest of the queue keeps its
            # placement — front-requeues preserve dispatch order and a
            # poison pill deliberately sits at the back, neither of
            # which is this trainer's to rewrite
            back.sort(key=lambda t: t.id)
            self.todo = back + self.todo
            if inflight_id is not None:
                for i, t in enumerate(self.todo):
                    if t.id == int(inflight_id):
                        self.todo.insert(0, self.todo.pop(i))
                        break
            self._snapshot()
            logger.info(
                "resume_lease(%s, pass %d): %d re-marked done, %d "
                "requeued, inflight=%s", trainer_id, pass_id, moved,
                requeued, inflight_id)
            return {"pass": self.cur_pass, "requeued": requeued,
                    "done": moved}

    def release_lease(self, trainer_id: str) -> int:
        """A live process whose training loop unwound mid-pass (a user
        exception, a NaN anomaly) abandons its work NOW: the in-flight
        lease and the parked uncommitted finishes requeue immediately.
        Liveness expiry cannot free them — the client (and its heartbeat
        thread) may stay open long after train() raised, renewing the
        trainer's liveness while the commit that would release the
        durability-gated pass roll can never come. Same ordering as the
        expiry path: todo = [finishes..., in-flight, ...rest] — both go
        through ``_requeue_trainer``. Liveness stays: the process is
        alive and may lease again. Returns how many tasks were
        requeued."""
        with self._lock:
            n = self._requeue_trainer(trainer_id, "lease released")
            if n:
                logger.info("trainer %s released its lease: %d task(s) "
                            "requeued", trainer_id, n)
            return n

    def request_save_model(self, trainer_id: str,
                           block_dur_s: float) -> bool:
        """Exactly-one-saver arbitration (`service.go:474`): the first
        requester in each ``block_dur_s`` window gets True."""
        with self._lock:
            now = time.monotonic()
            if now - self._last_save < block_dur_s:
                return False
            self._last_save = now
            logger.info("trainer %s elected to save the model", trainer_id)
            return True


# ----------------------------------------------------------------- RPC

def _send_msg(sock: socket.socket, obj: Any):
    if _chaos._ACTIVE is not None:
        _chaos._ACTIVE.hit("msg_send")
    data = json.dumps(obj).encode()
    sock.sendall(struct.pack(">I", len(data)) + data)


def _recv_msg(sock: socket.socket) -> Any:
    if _chaos._ACTIVE is not None:
        _chaos._ACTIVE.hit("msg_recv")
    hdr = _recv_exact(sock, 4)
    (n,) = struct.unpack(">I", hdr)
    return json.loads(_recv_exact(sock, n).decode())


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


# Explicit RPC surface — only these service methods are reachable over the
# socket (anything else, including non-callable attributes, is rejected).
RPC_METHODS = frozenset({
    "set_dataset", "get_task", "task_finished", "task_failed",
    "pass_finished", "request_save_model", "heartbeat", "commit_tasks",
    "current_pass", "resume_lease", "release_lease",
})


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        svc: MasterService = self.server.service  # type: ignore
        try:
            while True:
                req = _recv_msg(self.request)
                method = req["method"]
                kwargs = req.get("kwargs", {})
                try:
                    if method not in RPC_METHODS:
                        raise ValueError(f"unknown RPC method: {method!r}")
                    fn = getattr(svc, method)
                    if _trace._TRACER is not None:
                        # the server half of the training-side trace:
                        # parented under the trainer's rpc.<method>
                        # span via the envelope's "trace" field
                        parent = _trace.TraceContext.from_header(
                            req.get("trace"))
                        with _trace.span(f"rpc.server.{method}",
                                         parent=parent, method=method):
                            result = fn(**kwargs)
                    else:
                        result = fn(**kwargs)
                    _send_msg(self.request, {"ok": True, "result": result})
                except _chaos.ChaosDropped:
                    raise  # an injected loss of the RESPONSE: close the
                    # connection so the client's retry path exercises the
                    # duplicate-request (idempotency) guarantees
                except Exception as e:  # report, keep serving
                    _send_msg(self.request, {"ok": False, "error": str(e)})
        except (ConnectionError, OSError):
            pass


class MasterServer:
    """Threaded TCP server wrapping a MasterService."""

    def __init__(self, service: MasterService, host: str = "127.0.0.1",
                 port: int = 0):
        self.service = service
        # reuse must be set BEFORE bind — a restarted master (recovery)
        # re-binds its old port while client sockets sit in TIME_WAIT
        self._srv = socketserver.ThreadingTCPServer(
            (host, port), _Handler, bind_and_activate=False)
        self._srv.daemon_threads = True
        self._srv.allow_reuse_address = True
        self._srv.server_bind()
        self._srv.server_activate()
        self._srv.service = service  # type: ignore
        self.addr = self._srv.server_address
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()


class MasterClient:
    """Client with re-dial on connection loss (`go/connection/conn.go`).

    Retries use capped jittered exponential backoff: attempt n sleeps
    ``min(backoff_cap, retry_delay * 2**n) * uniform(0.5, 1.0)`` — a
    restarted master is not greeted by a synchronized retry storm from
    every trainer at once. Each delay is value-seeded from
    ``(trainer_id, method, attempt)`` — no shared jitter stream the
    training and heartbeat threads could interleave on — so a chaos
    run's retry timing reproduces from its seed.

    ``heartbeat_s`` arms a daemon thread renewing this trainer's task
    lease and liveness at that period (the etcd keepalive role); it
    starts lazily at the first ``get_task`` and stops at ``close``.
    It defaults ON (10 s — well inside the master's default 60 s
    ``trainer_timeout_s``): without a beat, a healthy trainer whose one
    task trains longer than the lease timeout is declared dead and its
    work requeued to a peer. Pass ``heartbeat_s=None`` (or 0) to
    disable, e.g. for a deliberately-silent test client."""

    def __init__(self, addr, *, retries: int = 10, retry_delay: float = 0.2,
                 backoff_cap: float = 5.0,
                 trainer_id: Optional[str] = None,
                 connect_timeout: float = 30.0,
                 heartbeat_s: Optional[float] = 10.0):
        self.addr = tuple(addr)
        self.retries = retries
        self.retry_delay = retry_delay
        self.backoff_cap = backoff_cap
        self.connect_timeout = connect_timeout
        self.heartbeat_s = heartbeat_s
        # identifies this client's task lease so a retried get_task after a
        # dropped response re-serves the same task instead of leaking it
        self.trainer_id = trainer_id or f"trainer-{os.getpid()}-{id(self):x}"
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_stop = threading.Event()

    def _connect(self):
        s = socket.create_connection(self.addr, timeout=self.connect_timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = s

    def _backoff(self, attempt: int, method: str = "") -> float:
        # value-seeded, not a shared Random stream: the training thread
        # and the heartbeat thread both redial concurrently, and their
        # scheduler-dependent interleaving on one stream would make the
        # same seed produce different backoff sequences run to run —
        # each delay depends only on (trainer_id, method, attempt), the
        # FaultPlan._bernoulli recipe, so chaos timing reproduces
        rng = random.Random(f"{self.trainer_id}:{method}:{attempt}")
        return backoff_delay(attempt, base=self.retry_delay,
                             cap=self.backoff_cap, rng=rng)

    def call(self, method: str, **kwargs):
        # one rpc.<method> span per call when tracing is armed — the
        # get_task / task_finished / heartbeat / commit spans of the
        # training side; the context rides the envelope's "trace"
        # field so the master's rpc.server.<method> span parents under
        # it. Guarded: the un-traced hot path pays one global load.
        if _trace._TRACER is not None:
            with _trace.span(f"rpc.{method}", method=method) as tctx:
                return self._call_retrying(method, kwargs, tctx)
        return self._call_retrying(method, kwargs, None)

    def _call_retrying(self, method: str, kwargs: dict, tctx):
        # the lock scopes ONE request/response exchange (no interleaved
        # frames from the heartbeat thread), NOT the whole retry cycle:
        # sleeping the backoff under the lock would block the training
        # thread's RPCs — and close() — for the full redial cycle while
        # the heartbeat thread waits out a master restart
        envelope = {"method": method, "kwargs": kwargs}
        if tctx is not None:
            envelope["trace"] = tctx.to_header()
        last = None
        for attempt in range(self.retries):
            try:
                with self._lock:
                    try:
                        if self._sock is None:
                            self._connect()
                        _send_msg(self._sock, envelope)
                        resp = _recv_msg(self._sock)
                    except (ConnectionError, OSError):
                        # a failed exchange leaves the socket desynced
                        # (request sent, response unread — or vice
                        # versa): it must be torn down before this lock
                        # RELEASES, or the heartbeat thread queued on
                        # the lock would run its own exchange on the
                        # desynced socket and read the stale response
                        # as its own, cross-wiring RPC results between
                        # threads
                        if self._sock is not None:
                            try:
                                self._sock.close()
                            except OSError:
                                pass
                        self._sock = None
                        raise
                if not resp["ok"]:
                    raise RuntimeError(resp["error"])
                return resp["result"]
            except (ConnectionError, OSError) as e:
                last = e
                if attempt + 1 >= self.retries:
                    break  # terminal failure: raise now, no dead sleep
                # interruptible: close() sets the event, so shutdown is
                # not held hostage by a redial cycle
                if self._hb_stop.wait(self._backoff(attempt, method)):
                    break
        raise ConnectionError(
            f"master at {self.addr} unreachable: {last}")

    # ---------------------------------------------------- heartbeats
    def _hb_loop(self):
        while not self._hb_stop.wait(self.heartbeat_s):
            try:
                self.call("heartbeat", trainer_id=self.trainer_id)
            except (ConnectionError, RuntimeError) as e:
                # the master may be mid-restart; the next beat retries
                logger.debug("heartbeat failed (will retry): %s", e)

    def start_heartbeat(self):
        if self.heartbeat_s and self._hb_thread is None:
            self._hb_stop.clear()
            self._hb_thread = threading.Thread(target=self._hb_loop,
                                               daemon=True)
            self._hb_thread.start()

    def close(self):
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2.0)
            self._hb_thread = None
        with self._lock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None

    # convenience wrappers
    def set_dataset(self, chunks):
        return self.call("set_dataset", chunks=chunks)

    def get_task(self, pass_id: int = 0):
        self.start_heartbeat()
        status, tdict = self.call("get_task", pass_id=pass_id,
                                  trainer_id=self.trainer_id)
        return status, (Task.from_dict(tdict) if tdict else None)

    def task_finished(self, task_id: int, defer_commit: bool = False):
        return self.call("task_finished", task_id=task_id,
                         trainer_id=self.trainer_id,
                         defer_commit=defer_commit)

    def task_failed(self, task_id: int):
        return self.call("task_failed", task_id=task_id)

    def pass_finished(self):
        return self.call("pass_finished")

    def commit_tasks(self, task_ids: Optional[List[int]] = None):
        return self.call("commit_tasks", trainer_id=self.trainer_id,
                         task_ids=task_ids)

    def heartbeat(self):
        return self.call("heartbeat", trainer_id=self.trainer_id)

    def current_pass(self) -> int:
        return self.call("current_pass")

    def resume_lease(self, pass_id: int, done_ids: List[int],
                     inflight_id: Optional[int] = None,
                     prev_trainer_id: Optional[str] = None):
        return self.call("resume_lease", trainer_id=self.trainer_id,
                         pass_id=pass_id, done_ids=list(done_ids),
                         inflight_id=inflight_id,
                         prev_trainer_id=prev_trainer_id)

    def release_lease(self):
        return self.call("release_lease", trainer_id=self.trainer_id)

    def request_save_model(self, trainer_id: str, block_dur_s: float):
        return self.call("request_save_model", trainer_id=trainer_id,
                         block_dur_s=block_dur_s)


def master_reader(client: MasterClient, load_chunk, *,
                  poll_s: float = 0.05, defer_commit: bool = True):
    """Reader over master-dispatched tasks (the v2
    `python/paddle/v2/master/client.py` role): pulls tasks, yields records
    from ``load_chunk(chunk)``, reports finish/failure. Each call of the
    returned reader streams one full pass; the pass counter advances
    across calls (the StartGetRecords(pass) protocol).

    The returned reader declares ``pass_aware = True``: the trainer calls
    it as ``reader(pass_id)`` so a checkpoint-resumed run requests the
    right pass from the master instead of getting an instant 'end' for
    already-finished ones.

    Exact-resume surface (consumed by ``SGD.train``; the fix for the
    old mid-pass caveat — records between a checkpoint and a crash are
    no longer lost within the interrupted pass):

    - ``ledger_state()`` — JSON-able position: the running pass, every
      task id finished so far in it, the in-flight task id and how many
      of its records have been yielded. The trainer stores this inside
      each checkpoint.
    - ``restore_ledger(ledger)`` — arm a resume: the next pass call
      sends ``resume_lease`` to the master (re-marking consumed tasks
      done, requeueing this trainer's post-checkpoint work, fronting
      the in-flight task) and skips the in-flight task's
      already-trained record prefix.
    - ``commit_ledger(ledger)`` — commit the finishes named by a (now
      durable) checkpoint's ledger; called by the checkpoint writer
      AFTER fsync, so the master never believes work durable that is
      not. ``None`` commits everything buffered (end-of-pass).
    - ``sync_pass(start)`` — reconcile a resumed trainer's start pass
      with the master's authoritative current pass, so a trainer whose
      cluster moved on neither replays nor starves on long-dead passes.

    ``defer_commit=True`` (default) parks finishes in the master's
    per-trainer uncommitted buffer until a commit; the master's pass
    roll WAITS on parked finishes (durability gate), so with no
    checkpointer wired (``checkpoint_coupled`` False) the reader
    commits its own buffer when its pass ends."""
    state = {"pass_id": 0, "run_pass": 0, "finished": [], "cur": None,
             "resume": None}

    def reader(pass_id: Optional[int] = None):
        my_pass = state["pass_id"] if pass_id is None else pass_id
        state["pass_id"] = my_pass + 1
        state["run_pass"] = my_pass
        skip_map = {}
        resume, state["resume"] = state["resume"], None
        if resume is not None and int(resume.get("pass", -1)) == my_pass:
            done_ids = [int(i) for i in resume.get("done", [])]
            inflight = resume.get("inflight")
            resp = client.resume_lease(
                my_pass, done_ids, inflight,
                prev_trainer_id=resume.get("trainer"))
            auth = (int(resp.get("pass", my_pass))
                    if isinstance(resp, dict) else my_pass)
            if auth == my_pass:
                state["finished"] = list(done_ids)
                if inflight is not None:
                    skip_map[int(inflight)] = int(resume.get("offset", 0))
            else:
                # the master's authoritative pass moved (a peer rolled
                # it, or a recovered master lost the run's progress):
                # the reconciliation no-oped, so NOTHING of our ledger
                # applies — in particular the in-flight record-prefix
                # skip, which would silently drop records the served
                # pass has never trained
                logger.warning(
                    "resume_lease no-oped (ledger pass %d, master pass "
                    "%d): discarding restored ledger, training the "
                    "served tasks in full", my_pass, auth)
                state["finished"] = []
        elif resume is not None and \
                0 <= int(resume.get("pass", -1)) < my_pass:
            # a COMPLETED pass's ledger (end-of-pass checkpoint made
            # durable, its commit RPC lost to the crash): the finishes
            # it names may still sit parked under the previous life's
            # id — with a stable trainer id, OUR OWN, whose liveness
            # every poll renews, so expiry can never free them — holding
            # the durability-gated roll of a pass the restored
            # parameters fully contain. Re-mark them done; the master
            # no-ops if that pass already rolled.
            done_ids = [int(i) for i in resume.get("done", [])]
            if done_ids:
                client.resume_lease(
                    int(resume["pass"]), done_ids, None,
                    prev_trainer_id=resume.get("trainer"))
            # and anything a previous life left parked at the CURRENT
            # pass (fresh boot with lost disk while the cluster moved
            # on): the empty reconcile requeues it, no-ops otherwise
            client.resume_lease(my_pass, [], None,
                                prev_trainer_id=resume.get("trainer"))
            state["finished"] = []
        else:
            state["finished"] = []
        while True:
            status, task = client.get_task(my_pass)
            if status == "end":
                # no checkpoint plane is driving commits (the trainer
                # sets ``checkpoint_coupled`` when it wires on_save):
                # commit the pass's finishes now, or the master's
                # durability-gated pass roll would wait on them forever
                if defer_commit and not reader.checkpoint_coupled:
                    client.commit_tasks()
                return
            if status == "wait":
                # "wait" can be the durability gate holding the pass
                # roll for OUR OWN uncommitted finishes — if the plane
                # that would commit them (the background checkpoint
                # writer) has died, polling would spin forever, each
                # poll renewing this trainer's liveness so not even the
                # lease timeout frees the work. The health check turns
                # that livelock into the writer's error.
                if reader.health_check is not None:
                    reader.health_check()
                time.sleep(poll_s)
                continue
            skip = skip_map.pop(task.id, 0)
            # epoch == the pass the master dispatched this copy in. A
            # MISMATCH means a liveness repair: the master served a
            # STALE pass's task (its owner died, no trainer at that
            # pass remains) to keep the job live. That work is not this
            # pass's: recorded in OUR ledger, a later crash-resume
            # would mark the task's recycled next-pass copy done
            # without the pass ever training it. It stays out of the
            # ledger (done AND inflight), and its finish commits
            # immediately — parked, no checkpoint of ours would ever
            # name it and the durability-gated pass roll would wait on
            # it forever.
            mine = getattr(task, "epoch", my_pass) == my_pass
            cur = [task.id, 0]
            state["cur"] = cur if mine else None
            try:
                n = 0
                for chunk in task.chunks:
                    for rec in load_chunk(chunk):
                        n += 1
                        cur[1] = n
                        if n <= skip:
                            continue  # already trained before the crash
                        yield rec
            except GeneratorExit:
                raise
            except Exception as e:
                logger.warning("task %d failed in reader: %s", task.id, e)
                state["cur"] = None
                client.task_failed(task.id)
            else:
                state["cur"] = None
                client.task_finished(task.id,
                                     defer_commit=defer_commit and mine)
                if mine:
                    state["finished"].append(task.id)

    def ledger_state():
        cur = state["cur"]
        return {"pass": state["run_pass"],
                "done": list(state["finished"]),
                "inflight": (cur[0] if cur else None),
                "offset": (cur[1] if cur else 0),
                # who wrote this ledger: resume_lease reconciles the
                # previous life's parked/committed work under this id
                # (the default id is pid-derived, new every restart)
                "trainer": client.trainer_id}

    def restore_ledger(ledger):
        state["resume"] = dict(ledger) if ledger else None

    def commit_ledger(ledger=None):
        if not defer_commit:
            return 0
        ids = None if ledger is None else ledger.get("done")
        return client.commit_tasks(task_ids=ids)

    def sync_pass(start_pass: int = 0) -> int:
        p = max(int(start_pass), int(client.current_pass()))
        state["pass_id"] = p
        return p

    reader.pass_aware = True
    # True once a checkpointer's on_save owns commits (set by SGD.train)
    reader.checkpoint_coupled = False
    # zero-arg callable raising if the commit plane is dead (SGD.train
    # wires the checkpointer's poll_error); polled while status=="wait"
    reader.health_check = None
    reader.ledger_state = ledger_state
    reader.restore_ledger = restore_ledger
    reader.commit_ledger = commit_ledger
    reader.sync_pass = sync_pass
    # called by SGD.train when the loop unwinds on a plain Exception:
    # the client (and its heartbeat) may stay open, so only an explicit
    # release frees the in-flight lease and parked finishes
    reader.release_lease = client.release_lease
    return reader


def main(argv: Optional[List[str]] = None) -> int:
    """Run a standalone master process (`go/master/master.go` role):

        python -m paddle_tpu.dist.master --port 8765 --store /path/snap

    The task queue recovers from ``--store`` on restart — kill the
    process and relaunch it and every in-flight lease requeues; clients
    redial with backoff. ``tools/chaos_soak.py`` drives exactly that."""
    import argparse
    import signal

    ap = argparse.ArgumentParser(prog="python -m paddle_tpu.dist.master")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--store", default="",
                    help="FileStore snapshot path (empty = in-memory)")
    ap.add_argument("--timeout_s", type=float, default=60.0)
    ap.add_argument("--trainer_timeout_s", type=float, default=None)
    ap.add_argument("--failure_max", type=int, default=3)
    ap.add_argument("--chunks_per_task", type=int, default=1)
    ap.add_argument("--straggle_after_s", default="auto",
                    help="seconds before a pending task is speculatively "
                         "re-served when todo is dry; 'auto' = "
                         "timeout_s/2, 'off' disables re-dispatch "
                         "(required when load_chunk has side effects "
                         "that must never run twice)")
    ap.add_argument("--metrics_port", type=int, default=0,
                    help="bind a /metrics exporter (Prometheus text + "
                         "?format=json) with the master's queue/lease "
                         "counters; 0 disables")
    args = ap.parse_args(argv)

    if args.straggle_after_s == "auto":
        straggle = _AUTO_STRAGGLE
    elif args.straggle_after_s in ("off", "none"):
        straggle = None
    else:
        straggle = float(args.straggle_after_s)
    _chaos.install_from_env()
    from paddle_tpu import obs
    obs.arm_from_env("master")
    store = FileStore(args.store) if args.store else None
    svc = MasterService(store=store, timeout_s=args.timeout_s,
                        trainer_timeout_s=args.trainer_timeout_s,
                        failure_max=args.failure_max,
                        chunks_per_task=args.chunks_per_task,
                        straggle_after_s=straggle)
    server = MasterServer(svc, host=args.host, port=args.port)
    metrics_srv = None
    if args.metrics_port:
        from paddle_tpu.obs import MetricsRegistry, serve_metrics
        registry = MetricsRegistry().register("master",
                                              svc.metrics_snapshot)
        metrics_srv = serve_metrics(registry, host=args.host,
                                    port=args.metrics_port)
        print(f"MASTER-METRICS {args.host}:"
              f"{metrics_srv.server_address[1]}", flush=True)
    print(f"MASTER {server.addr[0]}:{server.addr[1]}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    server.start()
    try:
        stop.wait()
    finally:
        if metrics_srv is not None:
            metrics_srv.shutdown()
            metrics_srv.server_close()
        server.stop()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
