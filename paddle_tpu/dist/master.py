"""Fault-tolerant master: elastic task dispatch with snapshot/recover.

Re-designs `go/master/service.go` for the TPU runtime. Semantics kept
one-for-one (cited by reference line):

- dataset pre-partitioned into tasks of N chunks (`service.go:106`)
- ``get_task`` dispatches todo→pending per pass (`service.go:368`)
- pending tasks carry a timeout; expiry requeues (`service.go:341-355`)
- ``task_failed`` requeues until ``failure_max`` then discards the task —
  poison-pill isolation (`service.go:313-335`)
- every queue mutation snapshots to the Store; a restarted master
  recovers and requeues pending work (`service.go:166,207`)
- ``request_save_model`` arbitration: exactly one trainer saves per
  window, so a dead "trainer 0" can't block checkpoints (`service.go:474`)

etcd is replaced by a ``Store`` interface (atomic checksummed file by
default — on cloud deployments this maps naturally onto GCS); Go net/rpc
+ gob becomes length-prefixed JSON over TCP; leader election is out of
scope for a single-master-per-job setup (the Store detects torn writes).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Dict, List, Optional

from paddle_tpu.utils.log import get_logger

logger = get_logger("dist.master")


@dataclasses.dataclass
class Task:
    id: int
    chunks: List[Any]          # opaque chunk descriptors (paths, ranges…)
    epoch: int = 0             # pass the task was last dispatched in
    num_failures: int = 0

    def to_dict(self):
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d):
        return cls(**d)


def partition_chunks(chunks: List[Any], chunks_per_task: int) -> List[Task]:
    """Pre-partition dataset chunks into tasks (`service.go:106`)."""
    if chunks_per_task <= 0:
        raise ValueError("chunks_per_task must be positive")
    tasks = []
    for i in range(0, len(chunks), chunks_per_task):
        tasks.append(Task(id=len(tasks), chunks=chunks[i:i + chunks_per_task]))
    return tasks


class InMemStore:
    """`go/master/inmem_store.go`: single-slot store for tests."""

    def __init__(self):
        self._buf: Optional[bytes] = None
        self._lock = threading.Lock()

    def save(self, data: bytes):
        with self._lock:
            self._buf = data

    def load(self) -> Optional[bytes]:
        with self._lock:
            return self._buf


class FileStore:
    """Atomic checksummed snapshot file (the etcd replacement).

    Write = tmp file + fsync + rename; an MD5 header detects torn/corrupt
    snapshots on load (the reference trusts etcd's consistency; a file
    needs the checksum — same guard as the pserver checkpoint's
    ``WrongChecksum``, `go/pserver/service.go:49`)."""

    def __init__(self, path: str):
        self.path = path

    def save(self, data: bytes):
        tmp = self.path + ".tmp"
        digest = hashlib.md5(data).hexdigest().encode()
        with open(tmp, "wb") as f:
            f.write(digest + b"\n" + data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def load(self) -> Optional[bytes]:
        try:
            with open(self.path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return None
        digest, _, data = raw.partition(b"\n")
        if hashlib.md5(data).hexdigest().encode() != digest:
            logger.warning("snapshot checksum mismatch at %s; ignoring",
                           self.path)
            return None
        return data


class MasterService:
    """The task-queue state machine. Thread-safe; every mutation
    snapshots to the store."""

    def __init__(self, store=None, *, timeout_s: float = 60.0,
                 failure_max: int = 3, chunks_per_task: int = 1):
        self.store = store or InMemStore()
        self.timeout_s = timeout_s
        self.failure_max = failure_max
        self.chunks_per_task = chunks_per_task
        self._lock = threading.RLock()
        self.todo: List[Task] = []
        self.pending: Dict[int, Task] = {}
        self._deadlines: Dict[int, float] = {}
        self._owner: Dict[str, int] = {}  # trainer_id -> leased task id
        self.done: List[Task] = []
        self.failed: List[Task] = []
        self.cur_pass = 0
        self._ready = False
        self._last_save: float = -1e30
        self._recover()

    # ------------------------------------------------------------ state

    def _snapshot_bytes(self) -> bytes:
        state = {
            "todo": [t.to_dict() for t in self.todo],
            "pending": [t.to_dict() for t in self.pending.values()],
            "done": [t.to_dict() for t in self.done],
            "failed": [t.to_dict() for t in self.failed],
            "cur_pass": self.cur_pass,
            "ready": self._ready,
        }
        return json.dumps(state).encode()

    def _snapshot(self):
        self.store.save(self._snapshot_bytes())

    def _recover(self):
        data = self.store.load()
        if not data:
            return
        state = json.loads(data.decode())
        self.todo = [Task.from_dict(d) for d in state["todo"]]
        # pending work was in flight when the master died → requeue
        # (`service.go:166` region: recovered state resets dispatch)
        self.todo.extend(Task.from_dict(d) for d in state["pending"])
        self.done = [Task.from_dict(d) for d in state["done"]]
        self.failed = [Task.from_dict(d) for d in state["failed"]]
        self.cur_pass = state["cur_pass"]
        self._ready = state["ready"]
        logger.info("master recovered: %d todo, %d done, %d failed, pass %d",
                    len(self.todo), len(self.done), len(self.failed),
                    self.cur_pass)

    # ------------------------------------------------------------- API

    def set_dataset(self, chunks: List[Any]):
        """Idempotent: only the first caller partitions (`service.go`
        SetDataset; later trainers' calls are no-ops once ready)."""
        with self._lock:
            if self._ready:
                return
            self.todo = partition_chunks(chunks, self.chunks_per_task)
            self._ready = True
            self._snapshot()

    def _release_owner(self, task_id: int):
        for trainer, tid in list(self._owner.items()):
            if tid == task_id:
                del self._owner[trainer]

    def _check_timeouts(self):
        now = time.monotonic()
        expired = [tid for tid, dl in self._deadlines.items() if dl <= now]
        for tid in expired:
            task = self.pending.pop(tid)
            del self._deadlines[tid]
            self._release_owner(tid)
            self._process_failure(task, "timeout")

    def _process_failure(self, task: Task, why: str):
        # `service.go:313` processFailedTask
        task.num_failures += 1
        if task.num_failures > self.failure_max:
            logger.warning("task %d discarded after %d failures (%s)",
                           task.id, task.num_failures, why)
            self.failed.append(task)
        else:
            logger.info("task %d requeued (%s, failure %d/%d)", task.id,
                        why, task.num_failures, self.failure_max)
            self.todo.append(task)
        self._snapshot()

    def get_task(self, pass_id: int = 0, trainer_id: Optional[str] = None):
        """("task", task_dict) | ("wait", None) | ("end", None).

        Pass-gated like the reference's per-pass record streams
        (`service.go:368` ErrPassBefore/ErrPassAfter): a trainer asks for
        tasks of ITS pass. "end" means that pass is fully resolved; "wait"
        means tasks are in flight elsewhere (or an earlier pass is still
        draining). The roll to the next pass happens when the first
        trainer asks for a later pass after a drain. A trainer that is a
        pass ahead may be served a straggler task requeued from the
        previous pass (at-least-once repair keeps the job live when the
        task's original owner died).

        ``trainer_id`` makes the call idempotent: if the caller already
        holds an unresolved task (its previous response was lost in a
        connection drop and the client re-sent the request), that same
        task is re-served with a fresh deadline instead of leaking a
        pending lease that would time out and count a spurious failure."""
        with self._lock:
            if not self._ready:
                return ("wait", None)
            self._check_timeouts()
            if trainer_id is not None and trainer_id in self._owner:
                tid = self._owner[trainer_id]
                if tid in self.pending:
                    self._deadlines[tid] = time.monotonic() + self.timeout_s
                    return ("task", self.pending[tid].to_dict())
            if pass_id < self.cur_pass:
                return ("end", None)
            if not self.todo:
                if self.pending:
                    return ("wait", None)
                if pass_id == self.cur_pass:
                    return ("end", None)
                # drained and the caller is a pass ahead → roll
                self.todo = self.done + self.failed
                for t in self.todo:
                    t.num_failures = 0
                self.done, self.failed = [], []
                self.cur_pass += 1
                self._snapshot()
            task = self.todo.pop(0)
            task.epoch = self.cur_pass
            self.pending[task.id] = task
            self._deadlines[task.id] = time.monotonic() + self.timeout_s
            if trainer_id is not None:
                self._owner[trainer_id] = task.id
            self._snapshot()
            return ("task", task.to_dict())

    def pass_finished(self) -> bool:
        """True when every task of the current pass is resolved."""
        with self._lock:
            self._check_timeouts()
            return self._ready and not self.todo and not self.pending

    def task_finished(self, task_id: int) -> bool:
        with self._lock:
            task = self.pending.pop(task_id, None)
            self._deadlines.pop(task_id, None)
            self._release_owner(task_id)
            if task is None:
                return False
            task.num_failures = 0
            self.done.append(task)
            self._snapshot()
            return True

    def task_failed(self, task_id: int) -> bool:
        with self._lock:
            task = self.pending.pop(task_id, None)
            self._deadlines.pop(task_id, None)
            self._release_owner(task_id)
            if task is None:
                return False
            self._process_failure(task, "reported")
            return True

    def request_save_model(self, trainer_id: str,
                           block_dur_s: float) -> bool:
        """Exactly-one-saver arbitration (`service.go:474`): the first
        requester in each ``block_dur_s`` window gets True."""
        with self._lock:
            now = time.monotonic()
            if now - self._last_save < block_dur_s:
                return False
            self._last_save = now
            logger.info("trainer %s elected to save the model", trainer_id)
            return True


# ----------------------------------------------------------------- RPC

def _send_msg(sock: socket.socket, obj: Any):
    data = json.dumps(obj).encode()
    sock.sendall(struct.pack(">I", len(data)) + data)


def _recv_msg(sock: socket.socket) -> Any:
    hdr = _recv_exact(sock, 4)
    (n,) = struct.unpack(">I", hdr)
    return json.loads(_recv_exact(sock, n).decode())


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


# Explicit RPC surface — only these service methods are reachable over the
# socket (anything else, including non-callable attributes, is rejected).
RPC_METHODS = frozenset({
    "set_dataset", "get_task", "task_finished", "task_failed",
    "pass_finished", "request_save_model",
})


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        svc: MasterService = self.server.service  # type: ignore
        try:
            while True:
                req = _recv_msg(self.request)
                method = req["method"]
                kwargs = req.get("kwargs", {})
                try:
                    if method not in RPC_METHODS:
                        raise ValueError(f"unknown RPC method: {method!r}")
                    fn = getattr(svc, method)
                    result = fn(**kwargs)
                    _send_msg(self.request, {"ok": True, "result": result})
                except Exception as e:  # report, keep serving
                    _send_msg(self.request, {"ok": False, "error": str(e)})
        except (ConnectionError, OSError):
            pass


class MasterServer:
    """Threaded TCP server wrapping a MasterService."""

    def __init__(self, service: MasterService, host: str = "127.0.0.1",
                 port: int = 0):
        self.service = service
        # reuse must be set BEFORE bind — a restarted master (recovery)
        # re-binds its old port while client sockets sit in TIME_WAIT
        self._srv = socketserver.ThreadingTCPServer(
            (host, port), _Handler, bind_and_activate=False)
        self._srv.daemon_threads = True
        self._srv.allow_reuse_address = True
        self._srv.server_bind()
        self._srv.server_activate()
        self._srv.service = service  # type: ignore
        self.addr = self._srv.server_address
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()


class MasterClient:
    """Client with re-dial on connection loss (`go/connection/conn.go`)."""

    def __init__(self, addr, *, retries: int = 10, retry_delay: float = 0.2,
                 trainer_id: Optional[str] = None,
                 connect_timeout: float = 30.0):
        self.addr = tuple(addr)
        self.retries = retries
        self.retry_delay = retry_delay
        self.connect_timeout = connect_timeout
        # identifies this client's task lease so a retried get_task after a
        # dropped response re-serves the same task instead of leaking it
        self.trainer_id = trainer_id or f"trainer-{os.getpid()}-{id(self):x}"
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def _connect(self):
        s = socket.create_connection(self.addr, timeout=self.connect_timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = s

    def call(self, method: str, **kwargs):
        with self._lock:
            last = None
            for _ in range(self.retries):
                try:
                    if self._sock is None:
                        self._connect()
                    _send_msg(self._sock, {"method": method,
                                           "kwargs": kwargs})
                    resp = _recv_msg(self._sock)
                    if not resp["ok"]:
                        raise RuntimeError(resp["error"])
                    return resp["result"]
                except (ConnectionError, OSError) as e:
                    last = e
                    self._sock = None
                    time.sleep(self.retry_delay)
            raise ConnectionError(
                f"master at {self.addr} unreachable: {last}")

    def close(self):
        with self._lock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None

    # convenience wrappers
    def set_dataset(self, chunks):
        return self.call("set_dataset", chunks=chunks)

    def get_task(self, pass_id: int = 0):
        status, tdict = self.call("get_task", pass_id=pass_id,
                                  trainer_id=self.trainer_id)
        return status, (Task.from_dict(tdict) if tdict else None)

    def task_finished(self, task_id: int):
        return self.call("task_finished", task_id=task_id)

    def task_failed(self, task_id: int):
        return self.call("task_failed", task_id=task_id)

    def pass_finished(self):
        return self.call("pass_finished")

    def request_save_model(self, trainer_id: str, block_dur_s: float):
        return self.call("request_save_model", trainer_id=trainer_id,
                         block_dur_s=block_dur_s)


def master_reader(client: MasterClient, load_chunk, *,
                  poll_s: float = 0.05):
    """Reader over master-dispatched tasks (the v2
    `python/paddle/v2/master/client.py` role): pulls tasks, yields records
    from ``load_chunk(chunk)``, reports finish/failure. Each call of the
    returned reader streams one full pass; the pass counter advances
    across calls (the StartGetRecords(pass) protocol).

    The returned reader declares ``pass_aware = True``: the trainer calls
    it as ``reader(pass_id)`` so a checkpoint-resumed run requests the
    right pass from the master instead of getting an instant 'end' for
    already-finished ones. Caveat (shared with the reference): within a
    pass the master does not re-serve tasks already finished, so a
    mid-pass checkpoint restored against a persistent master resumes with
    only that pass's *remaining* tasks — records between the checkpoint
    and the crash are trained at-least-once only across passes, not
    within the interrupted one."""
    state = {"pass_id": 0}

    def reader(pass_id: Optional[int] = None):
        my_pass = state["pass_id"] if pass_id is None else pass_id
        state["pass_id"] = my_pass + 1
        while True:
            status, task = client.get_task(my_pass)
            if status == "end":
                return
            if status == "wait":
                time.sleep(poll_s)
                continue
            try:
                for chunk in task.chunks:
                    for rec in load_chunk(chunk):
                        yield rec
            except GeneratorExit:
                raise
            except Exception as e:
                logger.warning("task %d failed in reader: %s", task.id, e)
                client.task_failed(task.id)
            else:
                client.task_finished(task.id)

    reader.pass_aware = True
    return reader
