"""Multi-host job launcher.

Plays ``paddle/scripts/cluster_train/paddle.py:63-157``: the reference
fabric script copies the job dir to every node and starts pservers +
trainers with the right ``--trainer_id``/``--pserver`` wiring. The TPU
equivalent starts one worker process per host wired with:

- the JAX **coordinator address** (process 0) + process count/index —
  what ``jax.distributed.initialize`` needs to form a multi-host SPMD
  job over ICI/DCN (the pserver endpoints' role);
- the **master endpoint** — the fault-tolerant task-dispatch service
  (dist/master.py, the Go master's role) feeding every worker's input
  pipeline.

Local mode (``launch_local``) spawns N processes on this machine — the
in-proc-pserver trick of ``test_TrainerOnePass.cpp:246-251`` at launcher
granularity — and is how the launcher is tested without a cluster.
Multi-host mode emits per-host commands (``build_host_commands``) with
the same environment contract; run them under ssh/k8s/gcloud.

Worker-side: ``init_from_env()`` reads the contract and (on real
multi-host TPU) calls ``jax.distributed.initialize``.

Environment contract (all set by the launcher):
  PADDLE_TPU_NUM_PROCESSES / PADDLE_TPU_PROCESS_ID
  PADDLE_TPU_COORDINATOR   host:port of process 0 (jax coordinator)
  PADDLE_TPU_MASTER        host:port of the task master ("" = none)
  PADDLE_TPU_DISTRIBUTED   "1" => init_from_env calls
                           jax.distributed.initialize (real pods; unset
                           for local CPU testing)
"""

from __future__ import annotations

import dataclasses
import os
import shlex
import socket
import subprocess
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclasses.dataclass
class LaunchContext:
    """What a launched worker knows about its job."""

    num_processes: int
    process_id: int
    coordinator: str
    master: str = ""

    @property
    def is_chief(self) -> bool:
        return self.process_id == 0

    def master_client(self, **kw):
        from paddle_tpu.dist.master import MasterClient
        if not self.master:
            raise RuntimeError("this job was launched without a master")
        host, _, port = self.master.rpartition(":")
        return MasterClient((host, int(port)),
                            trainer_id=f"trainer-{self.process_id}", **kw)


def init_from_env() -> LaunchContext:
    """Worker entry: parse the launcher's environment contract; on real
    multi-host accelerators (PADDLE_TPU_DISTRIBUTED=1) also bring up the
    JAX coordination service so pjit spans all hosts."""
    ctx = LaunchContext(
        num_processes=int(os.environ.get("PADDLE_TPU_NUM_PROCESSES", "1")),
        process_id=int(os.environ.get("PADDLE_TPU_PROCESS_ID", "0")),
        coordinator=os.environ.get("PADDLE_TPU_COORDINATOR", ""),
        master=os.environ.get("PADDLE_TPU_MASTER", ""))
    if os.environ.get("PADDLE_TPU_DISTRIBUTED") == "1":
        import jax
        try:
            # jaxlib >= 0.4.36 ships Gloo CPU collectives but does NOT
            # select them by default — without this, any cross-process
            # computation on the CPU backend dies with "Multiprocess
            # computations aren't implemented on the CPU backend" (the
            # local 2-process launcher test's failure mode). Set it
            # unconditionally: it only affects the CPU backend (TPU/GPU
            # jobs ignore it), and probing the platform here would
            # initialize a backend BEFORE distributed.initialize.
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except (AttributeError, ValueError):  # older jaxlib: no knob,
            pass                              # and no Gloo to select
        jax.distributed.initialize(
            coordinator_address=ctx.coordinator,
            num_processes=ctx.num_processes,
            process_id=ctx.process_id)
    return ctx


def _worker_env(base: Dict[str, str], *, nproc: int, pid: int,
                coordinator: str, master: str,
                distributed: bool) -> Dict[str, str]:
    env = dict(base)
    env.update({
        "PADDLE_TPU_NUM_PROCESSES": str(nproc),
        "PADDLE_TPU_PROCESS_ID": str(pid),
        "PADDLE_TPU_COORDINATOR": coordinator,
    })
    if master:
        env["PADDLE_TPU_MASTER"] = master
    else:  # keep an externally-provided endpoint from the caller's env
        env.setdefault("PADDLE_TPU_MASTER", "")
    if distributed:
        env["PADDLE_TPU_DISTRIBUTED"] = "1"
    return env


def launch_local(script: str, nproc: int, *,
                 script_args: Sequence[str] = (),
                 master_chunks: Optional[List[Any]] = None,
                 chunks_per_task: int = 1,
                 env: Optional[Dict[str, str]] = None,
                 timeout: float = 600.0,
                 distributed: bool = False) -> List[int]:
    """Spawn ``nproc`` local worker processes running ``script``; when
    ``master_chunks`` is given, host the task master in this process and
    wire every worker to it. Returns per-process exit codes."""
    from paddle_tpu.dist.master import MasterServer, MasterService
    coordinator = f"127.0.0.1:{_free_port()}"
    server = None
    master_addr = ""
    try:
        if master_chunks is not None:
            service = MasterService(chunks_per_task=chunks_per_task)
            service.set_dataset(list(master_chunks))
            server = MasterServer(service).start()
            master_addr = f"{server.addr[0]}:{server.addr[1]}"
        procs = []
        try:
            for pid in range(nproc):
                wenv = _worker_env(dict(env or os.environ), nproc=nproc,
                                   pid=pid, coordinator=coordinator,
                                   master=master_addr,
                                   distributed=distributed)
                procs.append(subprocess.Popen(
                    [sys.executable, script, *script_args], env=wenv))
        except OSError:
            for p in procs:  # don't orphan the already-spawned workers
                p.kill()
            raise
        # one shared deadline: a wedged fleet costs ONE timeout, not
        # nproc of them
        import time
        deadline = time.monotonic() + timeout
        rcs = []
        for p in procs:
            try:
                rcs.append(p.wait(
                    timeout=max(0.0, deadline - time.monotonic())))
            except subprocess.TimeoutExpired:
                p.kill()
                rcs.append(-9)
        return rcs
    finally:
        if server is not None:
            server.stop()


def build_host_commands(hosts: Sequence[str], script: str, *,
                        script_args: Sequence[str] = (),
                        coordinator_port: int = 8476,
                        master_addr: str = "",
                        distributed: bool = True
                        ) -> List[Tuple[str, str]]:
    """Per-host shell commands carrying the same environment contract —
    what the reference's fabric loop ran over ssh
    (``cluster_train/paddle.py:106-157``). Host 0 is the coordinator."""
    cmds = []
    coordinator = f"{hosts[0]}:{coordinator_port}"
    for pid, host in enumerate(hosts):
        env = _worker_env({}, nproc=len(hosts), pid=pid,
                          coordinator=coordinator, master=master_addr,
                          distributed=distributed)
        exports = " ".join(f"{k}={shlex.quote(v)}"
                           for k, v in sorted(env.items()))
        args = " ".join(shlex.quote(a) for a in (script, *script_args))
        cmds.append((host, f"env {exports} {shlex.quote(sys.executable)} "
                           f"{args}"))
    return cmds


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.dist.launch",
        description="Start a multi-process paddle_tpu job "
                    "(cluster_train/paddle.py role)")
    ap.add_argument("--nproc", type=int, default=1,
                    help="local worker process count")
    ap.add_argument("--hosts", default="",
                    help="comma-separated hosts: print per-host commands "
                         "instead of launching locally")
    ap.add_argument("--master", default="",
                    help="external master endpoint host:port")
    ap.add_argument("--distributed", default=None,
                    action=__import__("argparse").BooleanOptionalAction,
                    help="workers call jax.distributed.initialize "
                         "(default: on for --hosts, off locally)")
    ap.add_argument("script")
    ap.add_argument("script_args", nargs="*")
    args = ap.parse_args(argv)

    if args.hosts:
        for host, cmd in build_host_commands(
                args.hosts.split(","), args.script,
                script_args=args.script_args, master_addr=args.master,
                distributed=(args.distributed
                             if args.distributed is not None else True)):
            print(f"# {host}\n{cmd}")
        return 0
    rcs = launch_local(args.script, args.nproc,
                       script_args=args.script_args,
                       env={**os.environ,
                            **({"PADDLE_TPU_MASTER": args.master}
                               if args.master else {})},
                       distributed=bool(args.distributed))
    return 0 if all(rc == 0 for rc in rcs) else 1


if __name__ == "__main__":
    sys.exit(main())
