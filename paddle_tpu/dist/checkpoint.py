"""Periodic checkpoint/resume with crash recovery.

The Go pserver's checkpoint loop re-designed for the TPU trainer
(`go/pserver/service.go:75-84, 272+`): periodic snapshots with MD5
integrity + a metadata pointer, recovery picks the newest *intact*
checkpoint (a torn/corrupt latest falls back to the previous one —
``WrongChecksum`` guard, `service.go:49`), and old checkpoints are
garbage-collected. Exactly-one-writer arbitration plugs in via the
master's ``request_save_model`` (`go/master/service.go:474`) so any
trainer — not a hardcoded trainer 0 — can own a save.

Cadence mirrors the v1 trainer flags ``--saving_period`` (passes) and
``--saving_period_by_batches`` (`Trainer.cpp:454-462`).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, Optional, Tuple

from paddle_tpu.trainer.checkpoint import load_params, save_params
from paddle_tpu.utils.log import get_logger

logger = get_logger("dist.checkpoint")


class Checkpointer:
    """Cadenced, integrity-checked, garbage-collected checkpoint writer.

    ``should_save`` may be the master client's ``request_save_model``
    partial; default always-true (single-trainer)."""

    def __init__(self, directory: str, *, saving_period: int = 1,
                 saving_period_by_batches: Optional[int] = None,
                 keep: int = 3,
                 should_save: Optional[Callable[[], bool]] = None):
        self.dir = directory
        self.saving_period = max(1, saving_period)
        self.saving_period_by_batches = saving_period_by_batches
        self.keep = max(1, keep)
        self.should_save = should_save or (lambda: True)
        os.makedirs(self.dir, exist_ok=True)

    # ------------------------------------------------------------ write

    def _ckpt_path(self, pass_id: int, batch_id: int) -> str:
        return os.path.join(self.dir,
                            f"checkpoint-p{pass_id:05d}-b{batch_id:08d}")

    def save(self, params: Dict[str, Any], opt_state: Any, *,
             pass_id: int, batch_id: int = 0, end_of_pass: bool = False):
        """Unconditional save + pointer update + GC. ``opt_state`` may be
        a zero-arg callable producing the state — the trainer passes its
        ZeRO-1 slot-gather lazily so the (device-op) gather only runs for
        saves that are actually due (resolved by ``save_params``, the
        single owner of that protocol)."""
        path = self._ckpt_path(pass_id, batch_id)
        save_params(path, params, opt_state,
                    meta={"pass_id": pass_id, "batch_id": batch_id,
                          "end_of_pass": end_of_pass, "time": time.time()})
        # pointer written AFTER the data file is durable: recovery order
        # is pointer → verify → fall back to directory scan
        with open(os.path.join(self.dir, "LATEST.tmp"), "w") as f:
            f.write(os.path.basename(path))
            f.flush()
            os.fsync(f.fileno())
        os.replace(os.path.join(self.dir, "LATEST.tmp"),
                   os.path.join(self.dir, "LATEST"))
        self._gc()
        logger.info("checkpoint saved: %s", path)
        return path

    def maybe_save(self, params, opt_state, *, pass_id: int,
                   batch_id: int = 0, end_of_pass: bool = False) -> bool:
        """Cadence + arbitration gate around save()."""
        due = False
        if end_of_pass and (pass_id + 1) % self.saving_period == 0:
            due = True
        if (self.saving_period_by_batches and batch_id
                and batch_id % self.saving_period_by_batches == 0):
            due = True
        if not due or not self.should_save():
            return False
        self.save(params, opt_state, pass_id=pass_id, batch_id=batch_id,
                  end_of_pass=end_of_pass)
        return True

    def _latest_name(self):
        try:
            with open(os.path.join(self.dir, "LATEST")) as f:
                return f.read().strip() + ".npz"
        except FileNotFoundError:
            return None

    def _gc(self):
        # Keep by recency (mtime), not name: an end-of-pass save
        # (batch_id=0) is newer than same-pass batch-cadence saves despite
        # sorting first lexicographically. The LATEST target always stays.
        def mtime(n):
            try:
                return os.path.getmtime(os.path.join(self.dir, n))
            except OSError:
                return 0.0
        ckpts = sorted((n for n in os.listdir(self.dir)
                        if n.startswith("checkpoint-")
                        and n.endswith(".npz")), key=lambda n: (mtime(n), n))
        latest = self._latest_name()
        for name in ckpts[:-self.keep]:
            if name == latest:
                continue
            base = os.path.join(self.dir, name)
            for suffix in ("", ".meta"):
                try:
                    os.remove(base + suffix)
                except FileNotFoundError:
                    pass

    # ------------------------------------------------------------- read

    def _candidates(self):
        """Newest-first candidate list: the LATEST pointer target, then the
        directory scan by recency (covers a torn pointer write)."""
        names = []
        latest = self._latest_name()
        if latest:
            names.append(latest)

        def mtime(n):
            try:
                return os.path.getmtime(os.path.join(self.dir, n))
            except OSError:
                return 0.0
        scanned = sorted((n for n in os.listdir(self.dir)
                          if n.startswith("checkpoint-")
                          and n.endswith(".npz")),
                         key=lambda n: (mtime(n), n), reverse=True)
        names.extend(n for n in scanned if n not in names)
        return names

    def restore(self) -> Optional[Tuple[dict, dict, dict]]:
        """(params, opt_flat, meta) from the newest intact checkpoint, or
        None. Corrupt files are skipped with a warning (crash recovery)."""
        for name in self._candidates():
            path = os.path.join(self.dir, name)
            if not os.path.exists(path):
                continue
            try:
                params, opt_flat = load_params(path)
            except Exception as e:  # torn .npz raises BadZipFile etc. —
                # any unreadable candidate falls through to the previous one
                logger.warning("skipping corrupt checkpoint %s: %s", path, e)
                continue
            meta = {}
            if os.path.exists(path + ".meta"):
                with open(path + ".meta") as f:
                    meta = json.load(f)
            logger.info("restored checkpoint %s (pass %s batch %s)", path,
                        meta.get("pass_id"), meta.get("batch_id"))
            return params, opt_flat, meta
        return None
