"""Periodic checkpoint/resume with crash recovery.

The Go pserver's checkpoint loop re-designed for the TPU trainer
(`go/pserver/service.go:75-84, 272+`): periodic snapshots with MD5
integrity + a metadata pointer, recovery picks the newest *intact*
checkpoint (a torn/corrupt latest falls back to the previous one —
``WrongChecksum`` guard, `service.go:49`), and old checkpoints are
garbage-collected. Exactly-one-writer arbitration plugs in via the
master's ``request_save_model`` (`go/master/service.go:474`) so any
trainer — not a hardcoded trainer 0 — can own a save.

Cadence mirrors the v1 trainer flags ``--saving_period`` (passes) and
``--saving_period_by_batches`` (`Trainer.cpp:454-462`).

Chaos-hardening round additions:

- **generation order, not mtime**: GC and recovery order checkpoints by
  the (pass_id, batch_id) generation parsed from the file name —
  sub-second save bursts and clock skew can tie or invert mtimes, and
  an mtime-ordered GC can then delete the newest generation.
- **off-hot-path saves** (``background=True``): the device→host fetch
  (which must happen before the step loop donates the buffers away)
  stays synchronous, but serialization + fsync + rename + GC run on a
  single worker thread — the step loop never blocks on disk. ``flush``
  drains; ``restore`` flushes first; a worker failure re-raises at the
  next save/flush (the prefetch pipeline's error contract).
- **on_save callback**: fires AFTER a generation is durable (post
  fsync+rename), with that save's meta — the trainer uses it to commit
  the master's task ledger, so the master never believes work durable
  that is not (docs/fault_tolerance.md).
- **exact-resume payload**: ``trainer_state`` (RNG key, carried BPTT
  state, …) and the reader's task ``ledger`` ride inside the
  checkpoint (``trainer/checkpoint.py`` ``state::`` namespace / the
  ``.meta`` JSON).
- **strict recovery**: a checkpoint without its ``.meta`` sidecar is
  treated as torn (the data file alone cannot prove integrity), and a
  corrupt ``.meta`` falls through — restore lands on the previous
  intact generation, never on torn state.
- ``testing.chaos`` hook ``checkpoint`` fires per durable generation so
  a FaultPlan can truncate/bit-flip exactly the Nth save.
"""

from __future__ import annotations

import json
import os
import queue
import re
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from paddle_tpu.obs import flight as _flight
from paddle_tpu.testing import chaos as _chaos
from paddle_tpu.trainer.checkpoint import (load_checkpoint, snapshot_arrays,
                                           write_snapshot)
from paddle_tpu.utils.log import get_logger

logger = get_logger("dist.checkpoint")

_GEN_RE = re.compile(r"^checkpoint-p(\d+)-b(\d+)\.npz$")


def _gen_key(name: str):
    """Total order on checkpoint file names by training generation.

    (parsed?, pass_id, end_of_pass?, batch_id, name): batch-cadence
    saves of a pass order by batch, the end-of-pass save (batch 0 by
    construction — ``maybe_save`` only batch-saves at batch_id>0) is the
    newest of its pass. Foreign/unparseable names sort oldest. mtime is
    deliberately NOT consulted: same-second save bursts and clock skew
    tie or invert it."""
    m = _GEN_RE.match(name)
    if not m:
        return (0, 0, False, 0, name)
    pass_id, batch_id = int(m.group(1)), int(m.group(2))
    return (1, pass_id, batch_id == 0, batch_id, name)


class Checkpointer:
    """Cadenced, integrity-checked, garbage-collected checkpoint writer.

    ``should_save`` may be the master client's ``request_save_model``
    partial; default always-true (single-trainer)."""

    # minimum age before an orphaned '.tmp' is GC-swept: young .tmp
    # files may be another process's in-flight write (shared save dir)
    ORPHAN_TMP_AGE_S = 60.0

    def __init__(self, directory: str, *, saving_period: int = 1,
                 saving_period_by_batches: Optional[int] = None,
                 keep: int = 3,
                 should_save: Optional[Callable[[], bool]] = None,
                 background: bool = False,
                 on_save: Optional[Callable[[dict], None]] = None):
        self.dir = directory
        self.saving_period = max(1, saving_period)
        self.saving_period_by_batches = saving_period_by_batches
        self.keep = max(1, keep)
        self.should_save = should_save or (lambda: True)
        self.background = background
        self.on_save = on_save
        os.makedirs(self.dir, exist_ok=True)
        self._q: Optional[queue.Queue] = None
        self._worker: Optional[threading.Thread] = None
        self._worker_err: Optional[BaseException] = None

    # ------------------------------------------------------------ write

    def _ckpt_path(self, pass_id: int, batch_id: int) -> str:
        return os.path.join(self.dir,
                            f"checkpoint-p{pass_id:05d}-b{batch_id:08d}")

    def save(self, params: Dict[str, Any], opt_state: Any, *,
             pass_id: int, batch_id: int = 0, end_of_pass: bool = False,
             trainer_state: Optional[Any] = None,
             ledger: Optional[Any] = None):
        """Unconditional save + pointer update + GC. ``params``,
        ``opt_state``, ``trainer_state`` and ``ledger`` may be zero-arg
        callables producing their values — the trainer passes its ZeRO-1
        slot-gather lazily so the (device-op) gather only runs for saves
        that are actually due. All device access resolves HERE, on the
        caller's thread (the step loop donates those buffers right
        after); in background mode only the file I/O is deferred."""
        if ledger is not None and callable(ledger):
            ledger = ledger()
        meta = {"pass_id": pass_id, "batch_id": batch_id,
                "end_of_pass": end_of_pass, "time": time.time()}
        if ledger is not None:
            meta["ledger"] = ledger
        path = self._ckpt_path(pass_id, batch_id)
        arrays = snapshot_arrays(params, opt_state, trainer_state)
        if self.background:
            self._raise_worker_err()
            self._ensure_worker()
            try:
                self._q.put_nowait((path, arrays, meta))
            except queue.Full:
                logger.warning(
                    "checkpoint writer backlog (disk slower than the "
                    "save cadence): blocking the step loop until a "
                    "generation drains")
                self._q.put((path, arrays, meta))
        else:
            self._write(path, arrays, meta)
        return path

    def _write(self, path: str, arrays, meta: dict):
        real = write_snapshot(path, arrays, meta)
        # pointer written AFTER the data file is durable: recovery order
        # is pointer → verify → fall back to directory scan
        with open(os.path.join(self.dir, "LATEST.tmp"), "w") as f:
            f.write(os.path.basename(path))
            f.flush()
            os.fsync(f.fileno())
        os.replace(os.path.join(self.dir, "LATEST.tmp"),
                   os.path.join(self.dir, "LATEST"))
        self._gc()
        logger.info("checkpoint saved: %s", path)
        if _flight._ACTIVE is not None:
            # a generation turning durable is a postmortem anchor: the
            # commit-after-durable protocol and exact-resume both pivot
            # on WHICH generation existed when a kill landed
            _flight._ACTIVE.record("checkpoint_durable",
                                   path=os.path.basename(path),
                                   pass_id=meta.get("pass_id"),
                                   batch_id=meta.get("batch_id"))
        if _chaos._ACTIVE is not None:
            _chaos._ACTIVE.hit("checkpoint", path=real)
        if self.on_save is not None:
            self.on_save(meta)
        return path

    # ------------------------------------------------- background plumbing
    def _ensure_worker(self):
        if self._worker is not None and self._worker.is_alive():
            return
        # bounded: at most 2 generations in flight keeps worst-case host
        # memory at ~2 snapshots; a third save blocks (with a warning)
        # rather than silently dropping a due generation
        self._q = queue.Queue(maxsize=2)
        self._worker = threading.Thread(target=self._work, daemon=True,
                                        name="checkpoint-writer")
        self._worker.start()

    def _work(self):
        while True:
            item = self._q.get()
            try:
                if item is None:
                    return
                self._write(*item)
            except BaseException as e:  # surfaced at next save/flush
                self._worker_err = e
                logger.error("background checkpoint write failed: %r", e)
            finally:
                self._q.task_done()

    def _raise_worker_err(self):
        if self._worker_err is not None:
            err, self._worker_err = self._worker_err, None
            if not isinstance(err, Exception):
                # a chaos kill (BaseException, e.g. ChaosKilled) parked
                # by the worker thread: re-raise AS ITSELF so the kill
                # contract holds in background mode too — the run dies
                # with the kill's own unwind class at the next
                # save/flush (deterministic from the seed), not a
                # downgraded RuntimeError the step loop would survive
                raise err
            raise RuntimeError("background checkpoint writer failed") from err

    # public: wait loops that depend on a future on_save commit (the
    # master reader's durability-gated pass roll) poll this so a dead
    # writer surfaces as its error, not as a livelock
    poll_error = _raise_worker_err

    def flush(self):
        """Drain pending background writes (no-op when synchronous)."""
        if self._q is not None:
            self._q.join()
        self._raise_worker_err()

    def close(self):
        if self._worker is not None and self._worker.is_alive():
            self._q.join()
            self._q.put(None)
            self._worker.join(timeout=10.0)
        self._worker = None
        self._raise_worker_err()

    def maybe_save(self, params, opt_state, *, pass_id: int,
                   batch_id: int = 0, end_of_pass: bool = False,
                   trainer_state: Optional[Any] = None,
                   ledger: Optional[Any] = None) -> bool:
        """Cadence + arbitration gate around save()."""
        due = False
        if end_of_pass and (pass_id + 1) % self.saving_period == 0:
            due = True
        if (self.saving_period_by_batches and batch_id
                and batch_id % self.saving_period_by_batches == 0):
            due = True
        if not due or not self.should_save():
            return False
        self.save(params, opt_state, pass_id=pass_id, batch_id=batch_id,
                  end_of_pass=end_of_pass, trainer_state=trainer_state,
                  ledger=ledger)
        return True

    def _latest_name(self):
        try:
            with open(os.path.join(self.dir, "LATEST")) as f:
                return f.read().strip() + ".npz"
        except FileNotFoundError:
            return None

    def _scan(self):
        return [n for n in os.listdir(self.dir)
                if n.startswith("checkpoint-") and n.endswith(".npz")]

    def _gc(self):
        # Keep by GENERATION (parsed pass/batch, end-of-pass newest of
        # its pass), never by mtime: a sub-second save burst or clock
        # skew ties/inverts mtimes and an mtime GC can then delete the
        # newest generation. The LATEST target always stays.
        ckpts = sorted(self._scan(), key=_gen_key)
        latest = self._latest_name()
        for name in ckpts[:-self.keep]:
            if name == latest:
                continue
            base = os.path.join(self.dir, name)
            for suffix in ("", ".meta"):
                try:
                    os.remove(base + suffix)
                except FileNotFoundError:
                    pass
        # sweep orphaned .tmp files: a kill mid-write (exactly what the
        # chaos soak injects, repeatedly) leaves a full-model-sized
        # '...npz.tmp' / '...meta.tmp' behind, and nothing else ever
        # matches it. Within one process writes and GC serialize on one
        # thread, but the save dir may be SHARED by several trainers
        # (the request_save_model one-saver-per-window arbitration): a
        # fresh .tmp can be another process's in-flight write, and
        # deleting it would crash that trainer's os.replace. Only .tmp
        # files old enough that no live write plausibly owns them
        # (crash debris only grows older) are swept.
        now = time.time()
        for name in os.listdir(self.dir):
            if name.startswith("checkpoint-") and name.endswith(".tmp"):
                path = os.path.join(self.dir, name)
                try:
                    if now - os.path.getmtime(path) >= self.ORPHAN_TMP_AGE_S:
                        os.remove(path)
                except FileNotFoundError:
                    pass

    # ------------------------------------------------------------- read

    def _candidates(self):
        """Newest-first candidate list: the LATEST pointer target, then the
        directory scan by generation (covers a torn pointer write)."""
        names = []
        latest = self._latest_name()
        if latest:
            names.append(latest)
        scanned = sorted(self._scan(), key=_gen_key, reverse=True)
        names.extend(n for n in scanned if n not in names)
        return names

    def restore(self) -> Optional[Tuple[dict, dict, dict]]:
        """(params, opt_flat, meta) from the newest intact checkpoint, or
        None. Corrupt files are skipped with a warning (crash recovery).
        ``meta["trainer_state"]`` carries the exact-resume state arrays
        when the checkpoint has them; ``meta["ledger"]`` the reader's
        task-ledger position.

        Intact means data file AND ``.meta`` sidecar: a data file
        without its sidecar is a torn save (the sidecar is written last)
        and nothing can prove the data's integrity — it falls through to
        the previous generation rather than loading possibly-torn
        state."""
        self.flush()
        for name in self._candidates():
            path = os.path.join(self.dir, name)
            if not os.path.exists(path):
                continue
            if not os.path.exists(path + ".meta"):
                logger.warning(
                    "skipping checkpoint %s: no .meta sidecar (torn save "
                    "— integrity unprovable)", path)
                continue
            try:
                with open(path + ".meta") as f:
                    meta = json.load(f)
                # hand the parsed sidecar down for the MD5 check — one
                # read, and the verified bytes are the ones we return
                params, opt_flat, state = load_checkpoint(path, meta=meta)
            except Exception as e:  # torn .npz raises BadZipFile etc. —
                # any unreadable candidate falls through to the previous one
                logger.warning("skipping corrupt checkpoint %s: %s", path, e)
                continue
            if state:
                meta["trainer_state"] = state
            logger.info("restored checkpoint %s (pass %s batch %s)", path,
                        meta.get("pass_id"), meta.get("batch_id"))
            return params, opt_flat, meta
        return None
