"""Distributed services: fault-tolerant task dispatch + checkpointing.

TPU-native re-design of the reference's new-generation Go runtime
(`go/master`, `go/pserver` — SURVEY §5.3): the accelerator-fabric parts
(gradient aggregation, parameter sharding) are handled by XLA collectives
in `paddle_tpu.parallel`, while the parts that are orthogonal to the
fabric — elastic data dispatch, failure detection, checkpoint arbitration
— live here as host-side services with the same observable semantics:

- ``MasterService``: dataset partitioned into tasks; todo/pending/done/
  failed queues; per-task timeout requeue; poison-pill discard after
  ``failure_max``; state snapshot/recover through a ``Store``; exactly-one
  -trainer save-model arbitration (`go/master/service.go:106,313,368,474`).
- ``MasterServer``/``MasterClient``: length-prefixed JSON RPC over TCP
  with client re-dial (replacing Go net/rpc + etcd discovery;
  `go/connection/conn.go`).
- ``FileStore``: atomic, checksummed snapshot store (replacing etcd;
  `go/master/etcd_client.go`).
"""

from paddle_tpu.dist.master import (FileStore, InMemStore, MasterClient,
                                    MasterServer, MasterService, Task,
                                    master_reader, partition_chunks)

__all__ = [
    "MasterService", "MasterServer", "MasterClient", "Task",
    "InMemStore", "FileStore", "partition_chunks", "master_reader",
]
