"""Fused optimizer-update kernels (the dense Momentum/Adam chains).

Reference precedent: ``paddle/math/TrainingAlgorithmOp.cu`` fuses each
optimizer's whole elementwise update into one kernel; the jnp spelling
in ``optim/optimizers.py`` stages it as 6-10 separate HBM-bound HLOs
per parameter. ``apply_one`` is the single routing point: called from
``Optimizer._update_param``'s dense branch, so the replicated step, the
ZeRO-1 shard-wise update and the packed FSDP update all reuse it.

Contract (``docs/kernels.md``):

- the fallback IS ``Optimizer._apply_one`` — off-TPU (or for any
  optimizer/slot/dtype shape the kernels don't cover) the routing is
  the identity, bitwise by construction;
- the Pallas spelling is numerically the same chain; its outputs feed
  the same slot dict shape ``_update_param`` expects (``prune_mask``
  re-attachment happens in the caller, as for ``_apply_one``);
- operands flatten and zero-pad to ``[rows x LANE]`` tiles via
  ``concatenate`` (CLAUDE.md bit-stability note); the padded region is
  a fixed point of both chains (all-zero in, all-zero out — Adam's
  ``eps`` keeps the quotient finite), so the unpad slice is exact.

Traced scalars (lr / Adam's bias-corrected alpha) ride SMEM ``(1, 1)``
blocks; static hyper-parameters are kernel constants.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from paddle_tpu.ops import common


def _ceil_to(n: int, m: int) -> int:
    return -(-n // m) * m


def _pad_flat(x):
    """Flatten and zero-pad to an ``[R, LANE]`` tile, R a multiple of 8."""
    n = x.size
    cols = common.LANE
    rows = max(8, _ceil_to(-(-n // cols), 8))
    flat = jnp.reshape(x, (n,))
    pad = rows * cols - n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), x.dtype)])
    return jnp.reshape(flat, (rows, cols))


def _unpad_flat(y, like):
    return jnp.reshape(jnp.reshape(y, (-1,))[:like.size], like.shape)


def _smem_scalar(v):
    return jnp.reshape(jnp.asarray(v, jnp.float32), (1, 1))


def _specs(n_tiles, tile_shape, n_scalars):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    tile = pl.BlockSpec(tile_shape, lambda t: (0, 0),
                        memory_space=pltpu.VMEM)
    scalar = pl.BlockSpec((1, 1), lambda t: (0, 0),
                          memory_space=pltpu.SMEM)
    return [tile] * n_tiles + [scalar] * n_scalars, tile


def _eligible(arrays):
    shape = arrays[0].shape
    for a in arrays:
        if a.dtype != jnp.float32 or a.shape != shape:
            return False
    rows = max(8, _ceil_to(-(-arrays[0].size // common.LANE), 8))
    resident = (len(arrays) * 2) * rows * common.LANE * 4
    return common.use_pallas(resident)


# --------------------------------------------------------------- momentum

def _momentum_kernel(mu, p_ref, g_ref, m_ref, lr_ref, decay_ref,
                     p_out, m_out):
    lr = lr_ref[0, 0]
    decay = decay_ref[0, 0]
    mom = mu * m_ref[:] - lr * (g_ref[:] + decay * p_ref[:])
    p_out[:] = p_ref[:] + mom
    m_out[:] = mom


def _momentum_fused(p, g, m, lr, mu, decay):
    from jax.experimental import pallas as pl
    pp, gp, mp = _pad_flat(p), _pad_flat(g), _pad_flat(m)
    in_specs, tile = _specs(3, pp.shape, 2)
    p2, m2 = pl.pallas_call(
        functools.partial(_momentum_kernel, mu),
        grid=(1,),
        in_specs=in_specs,
        out_specs=(tile, tile),
        out_shape=(jax.ShapeDtypeStruct(pp.shape, jnp.float32),
                   jax.ShapeDtypeStruct(pp.shape, jnp.float32)),
        interpret=common.interpret(),
    )(pp, gp, mp, _smem_scalar(lr), _smem_scalar(decay))
    return _unpad_flat(p2, p), {"mom": _unpad_flat(m2, m)}


# ------------------------------------------------------------------- adam

def _adam_kernel(b1, b2, eps, p_ref, g_ref, m_ref, v_ref, alpha_ref,
                 decay_ref, p_out, m_out, v_out):
    alpha = alpha_ref[0, 0]
    decay = decay_ref[0, 0]
    g = g_ref[:] + decay * p_ref[:]
    mom = b1 * m_ref[:] + (1 - b1) * g
    v = b2 * v_ref[:] + (1 - b2) * jnp.square(g)
    p_out[:] = p_ref[:] - alpha * mom / (jnp.sqrt(v) + eps)
    m_out[:] = mom
    v_out[:] = v


def _adam_fused(p, g, m, v, lr, t, b1, b2, eps, decay):
    from jax.experimental import pallas as pl
    tf = t.astype(jnp.float32)
    # the bias correction is scalar math — hoisted out of the kernel
    alpha = lr * jnp.sqrt(1 - jnp.power(b2, tf)) / (1 - jnp.power(b1, tf))
    pp, gp, mp, vp = (_pad_flat(p), _pad_flat(g), _pad_flat(m),
                      _pad_flat(v))
    in_specs, tile = _specs(4, pp.shape, 2)
    p2, m2, v2 = pl.pallas_call(
        functools.partial(_adam_kernel, b1, b2, eps),
        grid=(1,),
        in_specs=in_specs,
        out_specs=(tile, tile, tile),
        out_shape=(jax.ShapeDtypeStruct(pp.shape, jnp.float32),) * 3,
        interpret=common.interpret(),
    )(pp, gp, mp, vp, _smem_scalar(alpha), _smem_scalar(decay))
    return _unpad_flat(p2, p), {"mom": _unpad_flat(m2, m),
                                "v": _unpad_flat(v2, v)}


# ---------------------------------------------------------------- routing

def apply_one(opt, p, g, slots, lr, decay, t):
    """Fused stand-in for ``opt._apply_one`` on the dense path. The slot
    dict may carry ``prune_mask`` (ignored here, re-attached by
    ``_update_param``, matching ``_apply_one``'s contract)."""
    from paddle_tpu.kernels import dispatch
    if not dispatch.fused_optimizer_enabled():
        return opt._apply_one(p, g, slots, lr, decay, t)
    kind = type(opt).__name__
    keys = set(slots) - {"prune_mask"}
    if (kind == "Momentum" and not getattr(opt, "nesterov", False)
            and keys == {"mom"} and _eligible((p, g, slots["mom"]))):
        return _momentum_fused(p, g, slots["mom"], lr, opt.momentum, decay)
    if (kind == "Adam" and keys == {"mom", "v"}
            and _eligible((p, g, slots["mom"], slots["v"]))):
        return _adam_fused(p, g, slots["mom"], slots["v"], lr, t,
                           opt.beta1, opt.beta2, opt.epsilon, decay)
    return opt._apply_one(p, g, slots, lr, decay, t)
