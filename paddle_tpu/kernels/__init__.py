"""Fused-kernel plane: Pallas-on-TPU cells with bitwise fallback
spellings, selected at trace time (``docs/kernels.md``).

No threads, no device state — pure trace-time dispatch (the pass-3
lock audit's scope assertion in ``tests/test_kernels.py`` pins this).
"""

from paddle_tpu.kernels import opt_update
from paddle_tpu.kernels.dispatch import (fused_optimizer,
                                         fused_optimizer_enabled,
                                         fused_rnn, rnn_cells_enabled,
                                         set_fused_optimizer,
                                         set_fused_rnn)
from paddle_tpu.kernels.rnn_cells import (gru_cell, gru_cell_infer,
                                          lstm_cell, lstm_cell_infer)

__all__ = [
    "opt_update", "lstm_cell", "gru_cell",
    "lstm_cell_infer", "gru_cell_infer",
    "fused_rnn", "fused_optimizer",
    "rnn_cells_enabled", "fused_optimizer_enabled",
    "set_fused_rnn", "set_fused_optimizer",
]
