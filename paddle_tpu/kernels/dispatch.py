"""Trace-time flags for the fused-kernel plane (``docs/kernels.md``).

Two independent switches, both resolved at TRACE time (they pick which
program gets staged, never a runtime branch):

- fused RNN cells (``--fused_rnn`` / ``PADDLE_TPU_FUSED_RNN``, default
  OFF): routes the non-default-activation LSTM/GRU cell math in
  ``layers/recurrent.py`` through ``kernels.rnn_cells``. The
  default-activation sequence paths already run the fused
  ``ops.lstm/gru`` recurrences and are unaffected.
- fused optimizer update (``PADDLE_TPU_FUSED_OPTIM``, default ON):
  routes the dense Momentum/Adam elementwise chain in
  ``optim/optimizers.py`` through ``kernels.opt_update``. Off-TPU the
  fused entry falls straight back to ``Optimizer._apply_one`` — the
  selection is bitwise-invisible there by construction.

Pallas-vs-reference selection within the plane rides the shared
``ops/common.py`` policy (``use_pallas``/``force_mode``), same as every
other kernel in the tree.
"""

from __future__ import annotations

import contextlib
import os


def _env_flag(name: str, default: bool) -> bool:
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() not in ("", "0", "off", "false", "no")


_FUSED_RNN = _env_flag("PADDLE_TPU_FUSED_RNN", False)
_FUSED_OPT = _env_flag("PADDLE_TPU_FUSED_OPTIM", True)


def rnn_cells_enabled() -> bool:
    return _FUSED_RNN


def fused_optimizer_enabled() -> bool:
    return _FUSED_OPT


def set_fused_rnn(flag: bool) -> None:
    global _FUSED_RNN
    _FUSED_RNN = bool(flag)


def set_fused_optimizer(flag: bool) -> None:
    global _FUSED_OPT
    _FUSED_OPT = bool(flag)


@contextlib.contextmanager
def fused_rnn(flag: bool = True):
    """Scope the fused-RNN-cell switch (tests and bench A/B sides)."""
    global _FUSED_RNN
    prev, _FUSED_RNN = _FUSED_RNN, bool(flag)
    try:
        yield
    finally:
        _FUSED_RNN = prev


@contextlib.contextmanager
def fused_optimizer(flag: bool = True):
    """Scope the fused-optimizer switch (tests and bench A/B sides)."""
    global _FUSED_OPT
    prev, _FUSED_OPT = _FUSED_OPT, bool(flag)
    try:
        yield
    finally:
        _FUSED_OPT = prev
