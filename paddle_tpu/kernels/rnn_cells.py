"""Fused LSTM/GRU cell kernels (one step, gates+activations+state).

The per-step counterpart of the fused sequence recurrences in
``ops/lstm.py``/``ops/gru.py``, for the paths that cannot use them: the
non-default-activation inline steps of ``layers/recurrent.py:LstmLayer/
GruLayer`` and the single-step ``LstmStepLayer``/``GruStepLayer``
(recurrent-group bodies), where the cell math is re-traced as a dozen
separate elementwise HLOs per step. Reference precedent:
``paddle/cuda/include/hl_gpu_lstm.cuh:46``/``hl_gpu_gru.cuh`` fuse the
same chain into one kernel launch.

Contract (``docs/kernels.md``):

- the reference spelling (``_lstm_math``/``_gru_math``) is the EXACT
  inline math of ``layers/recurrent.py`` — same ops in the same order —
  so routing a layer through the fallback is bitwise-invisible;
- the Pallas path is taken only at trace time (``common.use_pallas``,
  TPU or forced) and only for the default activation set; its backward
  is the ``jax.vjp`` of the reference spelling (recompute strategy —
  a one-step cell is cheap to recompute, residuals are the inputs);
- operands pad batch→multiple of 8 and hidden→multiple of ``LANE`` with
  zeros via ``concatenate`` (never ``jnp.pad``; CLAUDE.md bit-stability
  note), and the padded region provably stays finite for the default
  activations, so the ``[:B, :H]`` slice is the whole story.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from paddle_tpu.ops import common


def _act(name):
    # lazy import: kernels must stay importable without the layer plane
    from paddle_tpu.layers.activations import apply_activation
    return lambda x: apply_activation(name or "tanh", x)


def _ceil_to(n: int, m: int) -> int:
    return -(-n // m) * m


def _pad2(x, rows: int, cols: int):
    r, c = x.shape
    if c < cols:
        x = jnp.concatenate(
            [x, jnp.zeros((r, cols - c), x.dtype)], axis=1)
    if r < rows:
        x = jnp.concatenate(
            [x, jnp.zeros((rows - r, x.shape[1]), x.dtype)], axis=0)
    return x


# ------------------------------------------------------------------- LSTM

def _lstm_math(gates, c_prev, check_i, check_f, check_o,
               act_in, act_gate, act_state):
    """The inline LstmLayer/LstmStepLayer step, verbatim (gates already
    hold x_t + h @ w + gate_bias)."""
    g_in, g_ig, g_fg, g_og = jnp.split(gates, 4, axis=-1)
    g_in = act_in(g_in)
    g_ig = act_gate(g_ig + c_prev * check_i)
    g_fg = act_gate(g_fg + c_prev * check_f)
    state = g_in * g_ig + c_prev * g_fg
    g_og = act_gate(g_og + state * check_o)
    return g_og * act_state(state), state


def _lstm_ref_default(gates, c_prev, check_i, check_f, check_o):
    return _lstm_math(gates, c_prev, check_i, check_f, check_o,
                      _act("tanh"), _act("sigmoid"), _act("tanh"))


def _lstm_cell_kernel(gi_ref, gig_ref, gfg_ref, gog_ref, c_ref,
                      pI_ref, pF_ref, pO_ref, out_ref, state_ref):
    c = c_ref[:]
    i = jnp.tanh(gi_ref[:])
    ig = jax.nn.sigmoid(gig_ref[:] + c * pI_ref[0])
    fg = jax.nn.sigmoid(gfg_ref[:] + c * pF_ref[0])
    state = i * ig + c * fg
    og = jax.nn.sigmoid(gog_ref[:] + state * pO_ref[0])
    state_ref[:] = state
    out_ref[:] = og * jnp.tanh(state)


def _lstm_pallas(gates, c_prev, check_i, check_f, check_o):
    B, H = c_prev.shape
    Bp, Hp = _ceil_to(B, 8), _ceil_to(H, common.LANE)
    g_in, g_ig, g_fg, g_og = jnp.split(gates, 4, axis=-1)
    blocks = [_pad2(a, Bp, Hp) for a in (g_in, g_ig, g_fg, g_og, c_prev)]
    peeps = [_pad2(p.reshape(1, H), 1, Hp)
             for p in (check_i, check_f, check_o)]
    full = common.resident_block
    from jax.experimental import pallas as pl
    out, state = pl.pallas_call(
        _lstm_cell_kernel,
        grid=(1,),
        in_specs=[full(Bp, Hp)] * 5 + [full(1, Hp)] * 3,
        out_specs=(full(Bp, Hp), full(Bp, Hp)),
        out_shape=(jax.ShapeDtypeStruct((Bp, Hp), c_prev.dtype),
                   jax.ShapeDtypeStruct((Bp, Hp), c_prev.dtype)),
        interpret=common.interpret(),
    )(*blocks, *peeps)
    return out[:B, :H], state[:B, :H]


@jax.custom_vjp
def _lstm_fused(gates, c_prev, check_i, check_f, check_o):
    return _lstm_pallas(gates, c_prev, check_i, check_f, check_o)


def _lstm_fused_fwd(gates, c_prev, check_i, check_f, check_o):
    return (_lstm_fused(gates, c_prev, check_i, check_f, check_o),
            (gates, c_prev, check_i, check_f, check_o))


def _lstm_fused_bwd(res, ct):
    _, vjp = jax.vjp(_lstm_ref_default, *res)
    return vjp(ct)


_lstm_fused.defvjp(_lstm_fused_fwd, _lstm_fused_bwd)


def _lstm_pallas_ok(gates, c_prev, checks, default_acts):
    if not default_acts or gates.ndim != 2 or c_prev.ndim != 2:
        return False
    if any(p.ndim != 1 for p in checks):
        return False
    B, H = c_prev.shape
    Bp, Hp = _ceil_to(B, 8), _ceil_to(H, common.LANE)
    itemsize = jnp.dtype(c_prev.dtype).itemsize
    resident = (7 * Bp * Hp + 3 * Hp) * itemsize
    return common.use_pallas(resident)


def lstm_cell(gates, c_prev, check_i, check_f, check_o,
              act_input="tanh", act_gate="sigmoid", act_state="tanh"):
    """One LSTM step on pre-projected gates ``[B, 4H]`` with peephole
    diagonals ``[H]``; returns ``(out, state)``, both ``[B, H]``."""
    default = (act_input in ("tanh", "", None)
               and act_gate in ("sigmoid", "", None)
               and act_state in ("tanh", "", None))
    if _lstm_pallas_ok(gates, c_prev, (check_i, check_f, check_o), default):
        return _lstm_fused(gates, c_prev, check_i, check_f, check_o)
    return _lstm_math(gates, c_prev, check_i, check_f, check_o,
                      _act(act_input), _act(act_gate), _act(act_state))


# -------------------------------------------------------------------- GRU

def _gru_math(x, h, w_gate, w_state, act_in, act_gate):
    """The inline GruLayer/GruStepLayer step, verbatim (x already holds
    the input projection plus bias, ``[B, 3H]``)."""
    size = h.shape[-1]
    zr = x[:, :2 * size] + h @ w_gate
    z = act_gate(zr[:, :size])
    r = act_gate(zr[:, size:])
    c = act_in(x[:, 2 * size:] + (r * h) @ w_state)
    return h - z * h + z * c


def _gru_ref_default(x, h, w_gate, w_state):
    return _gru_math(x, h, w_gate, w_state, _act("tanh"), _act("sigmoid"))


def _gru_cell_kernel(xz_ref, xr_ref, xc_ref, h_ref, wz_ref, wr_ref,
                     wc_ref, out_ref):
    h = h_ref[:]
    z = jax.nn.sigmoid(
        xz_ref[:] + jnp.dot(h, wz_ref[:],
                            preferred_element_type=jnp.float32
                            ).astype(h.dtype))
    r = jax.nn.sigmoid(
        xr_ref[:] + jnp.dot(h, wr_ref[:],
                            preferred_element_type=jnp.float32
                            ).astype(h.dtype))
    c = jnp.tanh(
        xc_ref[:] + jnp.dot(r * h, wc_ref[:],
                            preferred_element_type=jnp.float32
                            ).astype(h.dtype))
    out_ref[:] = h - z * h + z * c


def _gru_pallas(x, h, w_gate, w_state):
    from jax.experimental import pallas as pl
    B, H = h.shape
    Bp, Hp = _ceil_to(B, 8), _ceil_to(H, common.LANE)
    xs = [_pad2(x[:, :H], Bp, Hp), _pad2(x[:, H:2 * H], Bp, Hp),
          _pad2(x[:, 2 * H:], Bp, Hp)]
    ws = [_pad2(w_gate[:, :H], Hp, Hp), _pad2(w_gate[:, H:], Hp, Hp),
          _pad2(w_state, Hp, Hp)]
    full = common.resident_block
    out = pl.pallas_call(
        _gru_cell_kernel,
        grid=(1,),
        in_specs=[full(Bp, Hp)] * 4 + [full(Hp, Hp)] * 3,
        out_specs=full(Bp, Hp),
        out_shape=jax.ShapeDtypeStruct((Bp, Hp), h.dtype),
        interpret=common.interpret(),
    )(*xs, _pad2(h, Bp, Hp), *ws)
    return out[:B, :H]


@jax.custom_vjp
def _gru_fused(x, h, w_gate, w_state):
    return _gru_pallas(x, h, w_gate, w_state)


def _gru_fused_fwd(x, h, w_gate, w_state):
    return _gru_fused(x, h, w_gate, w_state), (x, h, w_gate, w_state)


def _gru_fused_bwd(res, ct):
    _, vjp = jax.vjp(_gru_ref_default, *res)
    return vjp(ct)


_gru_fused.defvjp(_gru_fused_fwd, _gru_fused_bwd)


def _gru_pallas_ok(x, h, default_acts):
    if not default_acts or x.ndim != 2 or h.ndim != 2:
        return False
    B, H = h.shape
    Bp, Hp = _ceil_to(B, 8), _ceil_to(H, common.LANE)
    itemsize = jnp.dtype(h.dtype).itemsize
    resident = (5 * Bp * Hp + 3 * Hp * Hp) * itemsize
    return common.use_pallas(resident)


def gru_cell(x, h, w_gate, w_state, act_input="tanh", act_gate="sigmoid"):
    """One GRU step: ``x`` ``[B, 3H]`` (projection + bias pre-added),
    ``h`` ``[B, H]``, ``w_gate`` ``[H, 2H]``, ``w_state`` ``[H, H]``;
    returns the new hidden ``[B, H]``."""
    default = (act_input in ("tanh", "", None)
               and act_gate in ("sigmoid", "", None))
    if _gru_pallas_ok(x, h, default):
        return _gru_fused(x, h, w_gate, w_state)
    return _gru_math(x, h, w_gate, w_state,
                     _act(act_input), _act(act_gate))


# -------------------------------------------------- inference variants

def lstm_cell_infer(gates, c_prev, check_i, check_f, check_o,
                    act_input="tanh", act_gate="sigmoid",
                    act_state="tanh"):
    """``lstm_cell`` for the no-grad serving path: the PRIMAL spelling
    only. The training entry wraps the Pallas call in a ``custom_vjp``
    whose forward saves the full operand tuple as residuals and whose
    backward re-traces the reference math — plumbing a scoring/generate
    step never uses but still carries through tracing. This variant
    calls the Pallas primal directly: no residual tuple, no backward
    spelling in the program, and ``jax.grad`` through it fails loudly
    (``pallas_call`` has no AD rule), which PINS it to no-grad routing
    — layers select it only under ``train=False``. The fallback is the
    same verbatim inline math, so off-TPU routing stays bit-invisible
    (``docs/kernels.md``)."""
    default = (act_input in ("tanh", "", None)
               and act_gate in ("sigmoid", "", None)
               and act_state in ("tanh", "", None))
    if _lstm_pallas_ok(gates, c_prev, (check_i, check_f, check_o),
                       default):
        return _lstm_pallas(gates, c_prev, check_i, check_f, check_o)
    return _lstm_math(gates, c_prev, check_i, check_f, check_o,
                      _act(act_input), _act(act_gate), _act(act_state))


def gru_cell_infer(x, h, w_gate, w_state, act_input="tanh",
                   act_gate="sigmoid"):
    """``gru_cell`` for the no-grad serving path — primal-only, same
    contract as :func:`lstm_cell_infer` (no residuals, no backward
    spelling; ``jax.grad`` through the Pallas path fails loudly)."""
    default = (act_input in ("tanh", "", None)
               and act_gate in ("sigmoid", "", None))
    if _gru_pallas_ok(x, h, default):
        return _gru_pallas(x, h, w_gate, w_state)
    return _gru_math(x, h, w_gate, w_state,
                     _act(act_input), _act(act_gate))
