"""GAN training (`v1_api_demo/gan/gan_conf.py` + ``gan_trainer.py``).

The reference trains three config-sharing networks alternately (generator,
discriminator-on-real, generator+discriminator with frozen copies). The
TPU-native spelling: two graphs sharing parameters BY NAME —

- D-graph: x -> discriminator -> binary cost (trained on real=1 / fake=0)
- G-graph: noise -> generator -> the SAME discriminator layers with
  ``is_static`` params -> cost toward label 1

``GANTrainer`` alternates jitted steps and copies the discriminator's
fresh weights into the G-graph's static slots each round — the same
parameter flow as the reference's copy-between-gradient-machines loop.
"""

from __future__ import annotations

from typing import Dict

from paddle_tpu.config import dsl
from paddle_tpu.config.model_config import ParamAttr


def _generator(noise, *, hidden, data_dim):
    h = dsl.fc(input=noise, size=hidden, act="relu", name="g_h")
    return dsl.fc(input=h, size=data_dim, act="linear", name="g_out")


def _discriminator(x, *, hidden, static=False):
    def attr():
        return ParamAttr(is_static=True) if static else None

    h = dsl.fc(input=x, size=hidden, act="relu", name="d_h",
               param_attr=attr(), bias_attr=attr() or True)
    return dsl.fc(input=h, size=2, act="softmax", name="d_out",
                  param_attr=attr(), bias_attr=attr() or True)


def build_gan(*, noise_dim: int = 16, data_dim: int = 2, hidden: int = 64):
    """Returns (d_cost, g_cost) LayerOutputs living in two graphs."""
    dsl.reset()
    xin = dsl.data(name="x", size=data_dim)
    lab = dsl.data(name="label", size=2)
    d_cost = dsl.classification_cost(
        input=_discriminator(xin, hidden=hidden), label=lab, name="d_cost")
    d_graph = dsl.current_graph()

    dsl.reset()
    noise = dsl.data(name="noise", size=noise_dim)
    lab_g = dsl.data(name="label", size=2)
    fake = _generator(noise, hidden=hidden, data_dim=data_dim)
    g_cost = dsl.classification_cost(
        input=_discriminator(fake, hidden=hidden, static=True),
        label=lab_g, name="g_cost")
    g_graph = dsl.current_graph()
    return d_cost, g_cost, d_graph, g_graph


class GANTrainer:
    """Alternating GAN training driver (``gan_trainer.py``)."""

    def __init__(self, *, noise_dim: int = 16, data_dim: int = 2,
                 hidden: int = 64, lr: float = 1e-3, seed: int = 0):
        import jax
        from paddle_tpu.optim import Adam
        from paddle_tpu.trainer.trainer import SGD
        self.noise_dim = noise_dim
        d_cost, g_cost, _, _ = build_gan(
            noise_dim=noise_dim, data_dim=data_dim, hidden=hidden)
        self.d = SGD(cost=d_cost, update_equation=Adam(learning_rate=lr),
                     seed=seed)
        self.g = SGD(cost=g_cost, update_equation=Adam(learning_rate=lr),
                     seed=seed + 1)
        # start from one consistent discriminator
        self._push_d_into_g()
        self._rng = jax.random.PRNGKey(seed + 2)
        net = self.g.network
        from paddle_tpu.data.prefetch import RecompileGuard
        self._gen_fwd = jax.jit(
            lambda p, f: net.apply(p, f, train=False)["g_out"].value)
        # generate(n) compiles one variant per sample count — legal,
        # but a caller sweeping n would thrash silently without this
        self._gen_guard = RecompileGuard(self._gen_fwd, warn_after=8,
                                         name="gan_gen_fwd")

    def _push_d_into_g(self):
        for name, v in self.d.params.items():
            if name.startswith("_d_") and name in self.g.params:
                # copy: the D trainer's step donates its param buffers, so
                # sharing the array object would hand G a deleted buffer
                self.g.params[name] = v.copy()

    def generate(self, n: int):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.core.argument import Argument
        self._rng, k = jax.random.split(self._rng)
        noise = jax.random.normal(k, (n, self.noise_dim), jnp.float32)
        feed = {"noise": Argument(value=noise),
                "label": Argument(value=jnp.ones((n,), jnp.int32))}
        out = self._gen_fwd(self.g.params, feed), feed
        self._gen_guard.check()
        return out

    def train_round(self, real_batch) -> Dict[str, float]:
        """One alternation: D on real(1)+fake(0), then G toward 1."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.core.argument import Argument
        n = real_batch.shape[0]
        fake, g_feed = self.generate(n)

        def d_step(x, label):
            feed = {"x": Argument(value=x),
                    "label": Argument(value=label)}
            self._rng, k = jax.random.split(self._rng)
            self.d.params, self.d.opt_state, m = self.d._train_step(
                self.d.params, self.d.opt_state, feed, k, 0, None)
            return float(m["cost"])

        d_real = d_step(jnp.asarray(real_batch, jnp.float32),
                        jnp.ones((n,), jnp.int32))
        d_fake = d_step(jax.lax.stop_gradient(fake),
                        jnp.zeros((n,), jnp.int32))
        self._push_d_into_g()

        self._rng, k = jax.random.split(self._rng)
        self.g.params, self.g.opt_state, m = self.g._train_step(
            self.g.params, self.g.opt_state, g_feed, k, 0, None)
        return {"d_real": d_real, "d_fake": d_fake,
                "g": float(m["cost"])}
