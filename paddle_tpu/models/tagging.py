"""BiLSTM-CRF sequence tagger.

Mirrors the reference's sequence-tagging demo
(`v1_api_demo/sequence_tagging/rnn_crf.py`): embeddings -> forward +
backward recurrence -> linear CRF emission scores -> linear-chain CRF cost
(`paddle/gserver/layers/LinearChainCRF.cpp`) with a Viterbi decode branch
sharing the transition matrix. The recurrences run as ``lax.scan`` groups
(fused LSTM steps); CRF forward-backward is the chain kernel
(paddle_tpu/layers/chain.py).
"""

from __future__ import annotations

from paddle_tpu.config import dsl
from paddle_tpu.config.model_config import ParamAttr


def bilstm_crf_tagger(*, vocab_size: int = 5000, embed_dim: int = 64,
                      hidden: int = 64, num_labels: int = 9):
    """Returns (cost, decoded, data_names). ``decoded`` is the Viterbi
    path; the CRF transition matrix is shared between cost and decode by
    parameter name, as the reference shares it between ``crf_layer`` and
    ``crf_decoding_layer``."""
    word = dsl.data(name="word", size=vocab_size, is_sequence=True)
    label = dsl.data(name="label", size=num_labels, is_sequence=True)
    emb = dsl.embedding(input=word, size=embed_dim, name="word_emb")

    f_in = dsl.fc(input=emb, size=hidden * 4, act="linear", name="fwd_in")
    fwd = dsl.lstmemory(input=f_in, name="lstm_fwd")
    b_in = dsl.fc(input=emb, size=hidden * 4, act="linear", name="bwd_in")
    bwd = dsl.lstmemory(input=b_in, reverse=True, name="lstm_bwd")
    feat = dsl.concat([fwd, bwd], name="bilstm")

    emission = dsl.fc(input=feat, size=num_labels, act="linear",
                      name="emission", bias_attr=False)
    transitions = ParamAttr(name="crf_transitions")
    cost = dsl.crf_layer(input=emission, label=label, size=num_labels,
                         param_attr=transitions, name="crf_cost")
    decoded = dsl.crf_decoding_layer(input=emission, size=num_labels,
                                     param_attr=transitions,
                                     name="crf_decode")
    return cost, decoded, ["word", "label"]
