"""LSTM text classification — the reference's RNN benchmark model
(``benchmark/paddle/rnn/rnn.py``: embedding -> N x [fc(4h) + lstmemory] ->
max-pool over time -> fc softmax; IMDB, dict 30k, the 83 ms/batch headline
at ``benchmark/README.md:110-120``)."""

from __future__ import annotations

from paddle_tpu.config import dsl


def lstm_text_classifier(*, vocab_size: int = 30000, embed_dim: int = 128,
                         hidden: int = 256, num_layers: int = 2,
                         classes: int = 2):
    """Returns (cost, softmax_output, data_names)."""
    words = dsl.data(name="words", size=vocab_size, is_sequence=True)
    label = dsl.data(name="label", size=classes)
    x = dsl.embedding(input=words, size=embed_dim, vocab_size=vocab_size,
                      name="embed")
    for i in range(num_layers):
        proj = dsl.fc(input=x, size=hidden * 4, act="linear",
                      name=f"lstm{i}_proj")
        x = dsl.lstmemory(input=proj, name=f"lstm{i}")
    pooled = dsl.pooling(input=x, pooling_type="max", name="pool_time")
    out = dsl.fc(input=pooled, size=classes, act="softmax", name="output")
    cost = dsl.classification_cost(input=out, label=label, name="cost")
    return cost, out, ["words", "label"]
