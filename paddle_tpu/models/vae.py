"""Variational autoencoder (`v1_api_demo/vae/vae_conf.py`).

Encoder fc stack -> (mu, logvar) -> reparameterized sample (the
``sample_gaussian`` layer) -> decoder fc stack -> sigmoid reconstruction.
Training objective = reconstruction cross-entropy + KL(q || N(0,I)),
expressed as TWO cost layers trained on their sum (the multi-cost path)."""

from __future__ import annotations

from paddle_tpu.config import dsl
from paddle_tpu.config.model_config import Input, LayerDef


def _raw_layer(name, type_, inputs, **attrs):
    ld = LayerDef(name=name, type=type_,
                  inputs=[Input(i.name) for i in inputs], bias=False,
                  attrs=attrs)
    return dsl._add(ld)


def vae(*, data_dim: int = 784, hidden: int = 256, latent: int = 32):
    """Returns (costs, reconstruction, data_names). Train with
    ``SGD(cost=Topology(costs))`` — the trainer sums both costs."""
    x = dsl.data(name="x", size=data_dim)
    h = dsl.fc(input=x, size=hidden, act="relu", name="enc_h")
    mu = dsl.fc(input=h, size=latent, act="linear", name="enc_mu")
    logvar = dsl.fc(input=h, size=latent, act="linear", name="enc_logvar")
    z = _raw_layer("z", "sample_gaussian", [mu, logvar])
    dh = dsl.fc(input=z, size=hidden, act="relu", name="dec_h")
    recon = dsl.fc(input=dh, size=data_dim, act="sigmoid", name="recon")
    recon_cost = _raw_layer("recon_cost", "multi_binary_label_cross_entropy",
                            [recon, x])
    kl_cost = _raw_layer("kl_cost", "kl_gaussian", [mu, logvar])
    return [recon_cost, kl_cost], recon, ["x"]


def vae_decoder(*, data_dim: int = 784, hidden: int = 256,
                latent: int = 32):
    """Generation-mode graph: z -> reconstruction, sharing the decoder
    parameters (_dec_h.*, _recon.*) with the trained model."""
    z = dsl.data(name="z", size=latent)
    dh = dsl.fc(input=z, size=hidden, act="relu", name="dec_h")
    return dsl.fc(input=dh, size=data_dim, act="sigmoid", name="recon")
