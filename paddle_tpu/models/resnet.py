"""ResNet for ImageNet-scale image classification.

The BASELINE.json north-star model. The reference carries ResNet only as a
model-zoo feature-extraction config (``v1_api_demo/model_zoo/resnet/
resnet.py``, built from conv/batch_norm/addto layers of the v1 DSL); this is
the same topology expressed in this framework's DSL: bottleneck blocks,
projection shortcuts on stride changes, batch-norm after every conv.

TPU notes: NHWC layout, bf16-friendly (all compute is conv/matmul on the
MXU); global average pool via the sequence-free ``pool`` layer with full
window.
"""

from __future__ import annotations

from paddle_tpu.config import dsl

_DEPTH_CFG = {
    18: ("basic", [2, 2, 2, 2]),
    34: ("basic", [3, 4, 6, 3]),
    50: ("bottleneck", [3, 4, 6, 3]),
    101: ("bottleneck", [3, 4, 23, 3]),
    152: ("bottleneck", [3, 8, 36, 3]),
}


def _conv_bn(name, x, nf, fs, stride, act, channels=None):
    c = dsl.conv(input=x, num_filters=nf, filter_size=fs, stride=stride,
                 padding=(fs - 1) // 2, act="linear", channels=channels,
                 bias_attr=False, name=f"{name}_conv")
    return dsl.batch_norm(input=c, act=act, name=f"{name}_bn")


def _bottleneck(name, x, nf, stride, project):
    r = _conv_bn(f"{name}_a", x, nf, 1, stride, "relu")
    r = _conv_bn(f"{name}_b", r, nf, 3, 1, "relu")
    r = _conv_bn(f"{name}_c", r, nf * 4, 1, 1, "linear")
    sc = (_conv_bn(f"{name}_sc", x, nf * 4, 1, stride, "linear")
          if project else x)
    return dsl.addto([r, sc], act="relu", name=f"{name}_add")


def _basic(name, x, nf, stride, project):
    r = _conv_bn(f"{name}_a", x, nf, 3, stride, "relu")
    r = _conv_bn(f"{name}_b", r, nf, 3, 1, "linear")
    sc = (_conv_bn(f"{name}_sc", x, nf, 1, stride, "linear")
          if project else x)
    return dsl.addto([r, sc], act="relu", name=f"{name}_add")


def resnet(depth: int = 50, *, classes: int = 1000, image_size: int = 224,
           channels: int = 3, width: int = 64):
    """Returns (cost, softmax_output, data_names)."""
    kind, blocks = _DEPTH_CFG[depth]
    img = dsl.data(name="image", size=channels * image_size * image_size,
                   channels=channels, height=image_size, width=image_size)
    label = dsl.data(name="label", size=classes)
    x = _conv_bn("stem", img, width, 7, 2, "relu", channels=channels)
    x = dsl.img_pool(input=x, pool_size=3, stride=2, padding=1, name="stem_pool")
    block = _bottleneck if kind == "bottleneck" else _basic
    nf = width
    for stage, n in enumerate(blocks):
        for i in range(n):
            stride = 2 if (stage > 0 and i == 0) else 1
            project = (i == 0)
            x = block(f"res{stage+2}{chr(ord('a')+i)}", x, nf, stride, project)
        nf *= 2
    # global average pool over the remaining spatial extent
    x = dsl.img_pool(input=x, pool_type="avg-projection", name="global_pool")
    out = dsl.fc(input=x, size=classes, act="softmax", name="output")
    cost = dsl.classification_cost(input=out, label=label, name="cost")
    return cost, out, ["image", "label"]
