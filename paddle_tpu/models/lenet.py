"""LeNet-style MNIST conv net — the reference's ``v1_api_demo/mnist``
model (``mnist_conv_group.py`` / ``api_train.py`` topology: two conv+pool
stages then fc+softmax)."""

from __future__ import annotations

from paddle_tpu.config import dsl


def lenet_mnist(*, classes: int = 10):
    """Returns (cost, softmax_output, data_names). Graph is appended to the
    current DSL graph; call dsl.reset() first for a fresh model."""
    img = dsl.data(name="pixel", size=784, channels=1, height=28, width=28)
    label = dsl.data(name="label", size=classes)
    c1 = dsl.conv(input=img, num_filters=20, filter_size=5, act="relu",
                  channels=1, name="conv1")
    p1 = dsl.img_pool(input=c1, pool_size=2, stride=2, name="pool1")
    c2 = dsl.conv(input=p1, num_filters=50, filter_size=5, act="relu",
                  name="conv2")
    p2 = dsl.img_pool(input=c2, pool_size=2, stride=2, name="pool2")
    f1 = dsl.fc(input=p2, size=500, act="relu", name="fc1")
    out = dsl.fc(input=f1, size=classes, act="softmax", name="output")
    cost = dsl.classification_cost(input=out, label=label, name="cost")
    return cost, out, ["pixel", "label"]
