"""Seq2seq NMT with Bahdanau attention.

Mirrors the reference's seqToseq demo + ``simple_attention``
(`python/paddle/trainer_config_helpers/networks.py`): bidirectional GRU
encoder; GRU decoder driven by an additive-attention context each step;
generation via beam search (`RecurrentGradientMachine.cpp:1393`). Training
unrolls as a ``lax.scan`` recurrent group; generation runs through
``paddle_tpu.core.generation.SequenceGenerator`` as a jitted loop with
static beam dims.
"""

from __future__ import annotations

from paddle_tpu.config import dsl


def _attention(name, enc_seq, enc_proj, state, hidden):
    """Additive attention: score = v.tanh(W_d s + W_e h_t); returns the
    attention-weighted context vector (``simple_attention``)."""
    dproj = dsl.fc(input=state, size=hidden, act="linear",
                   name=f"{name}_dproj", bias_attr=False)
    expanded = dsl.expand(dproj, enc_proj, name=f"{name}_expand")
    comb = dsl.addto([expanded, enc_proj], act="tanh", name=f"{name}_comb")
    weight = dsl.fc(input=comb, size=1, act="sequence_softmax",
                    name=f"{name}_weight", bias_attr=False)
    scaled = dsl.scaling_layer(enc_seq, weight, name=f"{name}_scaled")
    return dsl.pooling(input=scaled, pooling_type="sum",
                       name=f"{name}_context")


def seq2seq_attention(*, src_vocab: int = 5000, trg_vocab: int = 5000,
                      embed_dim: int = 64, hidden: int = 64,
                      beam_size: int = 4, max_length: int = 20,
                      generating: bool = False,
                      seq_parallel: str = None, num_heads: int = 4):
    """Build the training graph (generating=False: returns (cost,
    probs_seq, data_names)) or the generation graph (generating=True:
    returns (gen_layer, data_names) — drive with SequenceGenerator).

    ``seq_parallel="ring"|"ulysses"`` adds an encoder self-attention
    block whose time dim shards over the trainer mesh's ``seq`` axis
    (``create_mesh(n_seq=...)``) — the long-context path for long
    source sequences. Off by default (goldens unchanged); without a
    seq-axis mesh the block runs dense."""
    src = dsl.data(name="source_words", size=src_vocab, is_sequence=True)
    semb = dsl.embedding(input=src, size=embed_dim, name="src_emb")
    if seq_parallel:
        semb = dsl.multi_head_attention(
            semb, num_heads=num_heads, seq_parallel=seq_parallel,
            name="enc_self_att")
    f_in = dsl.fc(input=semb, size=hidden * 3, act="linear", name="enc_f_in")
    fwd = dsl.grumemory(input=f_in, name="enc_fwd")
    b_in = dsl.fc(input=semb, size=hidden * 3, act="linear", name="enc_b_in")
    bwd = dsl.grumemory(input=b_in, reverse=True, name="enc_bwd")
    enc = dsl.concat([fwd, bwd], name="encoded")
    enc_proj = dsl.fc(input=enc, size=hidden, act="linear",
                      name="encoded_proj", bias_attr=False)
    # backward GRU's first frame summarizes the sentence -> decoder boot
    boot = dsl.fc(input=dsl.first_seq(bwd, name="enc_bwd_first"),
                  size=hidden, act="tanh", name="decoder_boot")

    def step(trg_emb, enc_static, proj_static):
        state = dsl.memory(name="gru_decoder", size=hidden,
                           boot_layer=boot)
        context = _attention("att", enc_static, proj_static, state, hidden)
        dec_in = dsl.fc(input=[context, trg_emb], size=hidden * 3,
                        act="linear", name="dec_in")
        gru = dsl.gru_step_layer(dec_in, state, size=hidden,
                                 name="gru_decoder")
        return dsl.fc(input=gru, size=trg_vocab, act="softmax",
                      name="dec_out", bias_attr=False)

    if generating:
        gen = dsl.beam_search(
            step,
            [dsl.GeneratedInput(size=trg_vocab,
                                embedding_name="_trg_emb.w0",
                                embedding_size=embed_dim),
             dsl.StaticInput(enc), dsl.StaticInput(enc_proj)],
            bos_id=0, eos_id=1, beam_size=beam_size,
            max_length=max_length, name="gen")
        return gen, ["source_words"]

    trg = dsl.data(name="target_words", size=trg_vocab, is_sequence=True)
    trg_next = dsl.data(name="target_next", size=trg_vocab,
                        is_sequence=True)
    temb = dsl.embedding(input=trg, size=embed_dim, name="trg_emb")
    probs = dsl.recurrent_group(
        step, [temb, dsl.StaticInput(enc), dsl.StaticInput(enc_proj)],
        name="decoder_group")
    cost = dsl.classification_cost(input=probs, label=trg_next,
                                   name="nmt_cost")
    return cost, probs, ["source_words", "target_words", "target_next"]
