from paddle_tpu.models.ctr import ctr_model  # noqa: F401
from paddle_tpu.models.gan import GANTrainer, build_gan  # noqa: F401
from paddle_tpu.models.lenet import lenet_mnist  # noqa: F401
from paddle_tpu.models.resnet import resnet  # noqa: F401
from paddle_tpu.models.lstm_text import lstm_text_classifier  # noqa: F401
from paddle_tpu.models.seq2seq import seq2seq_attention  # noqa: F401
from paddle_tpu.models.tagging import bilstm_crf_tagger  # noqa: F401
from paddle_tpu.models.vae import vae, vae_decoder  # noqa: F401
