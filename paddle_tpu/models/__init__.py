from paddle_tpu.models.lenet import lenet_mnist  # noqa: F401
from paddle_tpu.models.resnet import resnet  # noqa: F401
from paddle_tpu.models.lstm_text import lstm_text_classifier  # noqa: F401
