"""Sparse-embedding text CTR model.

Mirrors the reference's quick_start demo family
(`v1_api_demo/quick_start/trainer_config.emb.py` /
`trainer_config.lstm.py`): word-id sequence -> embedding -> sequence
pooling -> fc -> binary classification. The embedding table is flagged
``sparse_grad`` — the reference's sparse remote-update story
(`SparseRowMatrix.h:204`, `RemoteParameterUpdater.h:265`) — which here
selects the lazy touched-rows-only optimizer path and, under a mesh,
automatic row-sharding over the model axis (parallel/mesh.effective_rules).
"""

from __future__ import annotations

from paddle_tpu.config import dsl
from paddle_tpu.config.model_config import ParamAttr


def ctr_model(*, vocab_size: int = 10000, embed_dim: int = 64,
              hidden: int = 128, classes: int = 2):
    """Returns (cost, softmax_output, data_names)."""
    words = dsl.data(name="words", size=vocab_size, is_sequence=True)
    label = dsl.data(name="label", size=classes)
    emb = dsl.embedding(input=words, size=embed_dim, vocab_size=vocab_size,
                        name="embed",
                        param_attr=ParamAttr(sparse_grad=True))
    pooled = dsl.pooling(input=emb, pooling_type="average", name="avg_pool")
    h = dsl.fc(input=pooled, size=hidden, act="relu", name="hidden")
    out = dsl.fc(input=h, size=classes, act="softmax", name="output")
    cost = dsl.classification_cost(input=out, label=label, name="cost")
    return cost, out, ["words", "label"]
