from paddle_tpu.config.model_config import Input, LayerDef, ModelDef  # noqa: F401
