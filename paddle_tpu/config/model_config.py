"""Model configuration graph — the contract between the DSL and the executor.

Plays the role of the reference's ``ModelConfig`` protobuf (``proto/
ModelConfig.proto``: ``LayerConfig`` + per-type sub-configs), produced there
by ``config_parser.py`` and consumed by ``GradientMachine::create``. Here the
config is plain Python dataclasses: the DSL builds a ``ModelDef``; the
``Network`` executor (core/network.py) turns it into a jittable function.

Parameter naming follows the reference convention so checkpoints are
recognizable: input weight i of layer L is ``_L.w{i}``, bias is ``_L.wbias``
(see ``python/paddle/trainer/config_parser.py`` Layer.create_input_parameter).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Union


@dataclasses.dataclass
class ParamAttr:
    """Per-parameter attributes (``proto/ParameterConfig.proto``)."""

    name: Optional[str] = None  # explicit name => parameter sharing
    init: str = "normal"
    initial_mean: float = 0.0
    initial_std: Optional[float] = None
    is_static: bool = False
    learning_rate: float = 1.0
    l1_rate: Optional[float] = None
    l2_rate: Optional[float] = None
    sparse_grad: bool = False
    # StaticPruningHook (ParameterUpdaterHook.cpp:39): fraction of weights
    # masked to zero (smallest |w| at init) and kept zero by the optimizer
    sparsity_ratio: Optional[float] = None
    # True when this attr was synthesized from parse-wide defaults
    # (default_initial_std()...) rather than written at the layer: such
    # attrs must not clobber const-initialized specs (batch-norm gamma)
    from_defaults: bool = False


@dataclasses.dataclass
class Input:
    """One input connection of a layer (``LayerConfig.inputs``)."""

    layer_name: str
    param_attr: Optional[ParamAttr] = None
    # projection/operator spec for mixed layers, conv spec for conv layers...
    extra: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class LayerDef:
    """One layer (``LayerConfig`` in ``proto/ModelConfig.proto``)."""

    name: str
    type: str
    inputs: List[Input] = dataclasses.field(default_factory=list)
    size: Optional[int] = None
    act: str = "linear"
    bias: Union[bool, ParamAttr] = True
    drop_rate: float = 0.0
    attrs: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def input_names(self) -> List[str]:
        return [i.layer_name for i in self.inputs]


@dataclasses.dataclass
class ModelDef:
    """The full graph (``ModelConfig``)."""

    layers: Dict[str, LayerDef] = dataclasses.field(default_factory=dict)
    input_layer_names: List[str] = dataclasses.field(default_factory=list)
    output_layer_names: List[str] = dataclasses.field(default_factory=list)
    # EvaluatorConfig-shaped dicts ({"type", "name", "input_layers", ...});
    # consumed by the trainer's metric wiring (SGD._host_evals)
    evaluators: List[Dict[str, Any]] = dataclasses.field(default_factory=list)

    def add(self, layer: LayerDef) -> LayerDef:
        if layer.name in self.layers:
            raise ValueError(f"duplicate layer name {layer.name!r}")
        self.layers[layer.name] = layer
        if layer.type == "data":
            self.input_layer_names.append(layer.name)
        return layer

    def topo_order(self, targets: Optional[List[str]] = None) -> List[str]:
        """Topological order of the sub-graph reaching ``targets`` (defaults
        to output_layer_names, else all layers). Mirrors the layer ordering
        the config parser emits for ``NeuralNetwork``'s forward loop
        (``paddle/gserver/gradientmachines/NeuralNetwork.cpp:235``)."""
        if targets is None:
            targets = self.output_layer_names or list(self.layers)
        order: List[str] = []
        seen: Dict[str, int] = {}  # 0=visiting, 1=done

        def visit(name: str):
            st = seen.get(name)
            if st == 1:
                return
            if st == 0:
                raise ValueError(f"cycle through layer {name!r}")
            if name not in self.layers:
                raise KeyError(f"layer {name!r} referenced but not defined")
            seen[name] = 0
            for dep in self.layers[name].input_names():
                visit(dep)
            seen[name] = 1
            order.append(name)

        for t in targets:
            visit(t)
        return order
