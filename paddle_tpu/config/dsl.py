"""Layer-construction DSL — the user API for building model graphs.

Role of ``python/paddle/trainer_config_helpers/layers.py`` (the v1 DSL) and
``python/paddle/v2/layer.py`` (its v2 graph-object wrapper): each function
appends a ``LayerDef`` to the active ``ModelDef`` and returns a
``LayerOutput`` handle usable as ``input=`` of later calls. Auto-generated
names follow the reference convention (``__fc_layer_0__``).

Unlike the reference there is no protobuf round-trip: the ModelDef *is* the
config; ``Topology``/``Network`` consume it directly.
"""

from __future__ import annotations

import contextlib
import dataclasses
import itertools
from typing import Any, Dict, List, Optional, Sequence, Union

from paddle_tpu.config.model_config import (Input, LayerDef, ModelDef,
                                            ParamAttr)

_GRAPH = ModelDef()
_COUNTERS: Dict[str, itertools.count] = {}


def reset():
    """Start a fresh graph (the reference resets config_parser globals per
    parse_config call)."""
    global _GRAPH, _COUNTERS
    _GRAPH = ModelDef()
    _COUNTERS = {}
    _SHAPES.clear()


def current_graph() -> ModelDef:
    return _GRAPH


def _auto_name(type_name: str) -> str:
    c = _COUNTERS.setdefault(type_name, itertools.count())
    return f"__{type_name}_layer_{next(c)}__"


@dataclasses.dataclass(frozen=True)
class LayerOutput:
    name: str
    size: int

    def __repr__(self):
        return f"LayerOutput({self.name!r}, size={self.size})"


def _in(x) -> List[LayerOutput]:
    if isinstance(x, LayerOutput):
        return [x]
    return list(x)


def _add(ldef: LayerDef) -> LayerOutput:
    _GRAPH.add(ldef)
    from paddle_tpu.core.registry import get_layer_impl
    # resolve output size via the impl's shape inference
    net_order = [i.layer_name for i in ldef.inputs]
    infos = []
    for n in net_order:
        infos.append(_shape_of(n))
    info = get_layer_impl(ldef.type).infer(ldef, infos)
    _SHAPES[ldef.name] = info
    return LayerOutput(ldef.name, info.size)


_SHAPES: Dict[str, Any] = {}


def _shape_of(name: str):
    return _SHAPES[name]


def _param(attr) -> Optional[ParamAttr]:
    if attr is None or isinstance(attr, ParamAttr):
        return attr
    if isinstance(attr, dict):
        return ParamAttr(**attr)
    raise TypeError(f"bad param attr {attr!r}")


# ----------------------------------------------------------------- layers
def data(name: str, size: int, *, height: int = None, width: int = None,
         channels: int = None, is_sequence: bool = False) -> LayerOutput:
    ldef = LayerDef(name=name, type="data", size=size, bias=False,
                    attrs={"height": height, "width": width,
                           "channels": channels, "is_sequence": is_sequence})
    return _add(ldef)


def fc(input, size: int, *, act: str = "tanh", name: str = None,
       bias_attr=True, param_attr=None, layer_attr: dict = None) -> LayerOutput:
    ins = [Input(i.name, param_attr=_param(param_attr)) for i in _in(input)]
    ldef = LayerDef(name=name or _auto_name("fc"), type="fc", inputs=ins,
                    size=size, act=act, bias=_bias(bias_attr),
                    **_layer_attr(layer_attr))
    return _add(ldef)


def embedding(input, size: int, *, vocab_size: int = None, name: str = None,
              param_attr=None) -> LayerOutput:
    src = _in(input)[0]
    vocab = vocab_size or _shape_of(src.name).size
    ldef = LayerDef(name=name or _auto_name("embedding"), type="embedding",
                    inputs=[Input(src.name, param_attr=_param(param_attr))],
                    size=size, bias=False, attrs={"vocab_size": vocab})
    return _add(ldef)


def mixed(inputs: Sequence, size: int, *, projections: Sequence[dict],
          act: str = "linear", name: str = None, bias_attr=False) -> LayerOutput:
    ins = [Input(i.name, param_attr=_param(p.pop("param_attr", None)))
           for i, p in zip(_in(inputs), [dict(p) for p in projections])]
    ldef = LayerDef(name=name or _auto_name("mixed"), type="mixed",
                    inputs=ins, size=size, act=act, bias=_bias(bias_attr),
                    attrs={"projections": list(projections)})
    return _add(ldef)


def conv(input, *, num_filters: int, filter_size: int, stride: int = 1,
         padding: int = 0, groups: int = 1, channels: int = None,
         act: str = "relu", name: str = None, bias_attr=True,
         param_attr=None, layer_type: str = "exconv") -> LayerOutput:
    src = _in(input)[0]
    extra = {"filter_size": filter_size, "stride": stride,
             "padding": padding, "groups": groups}
    if channels:
        extra["channels"] = channels
    ldef = LayerDef(name=name or _auto_name("conv"), type=layer_type,
                    inputs=[Input(src.name, param_attr=_param(param_attr),
                                  extra=extra)],
                    act=act, bias=_bias(bias_attr),
                    attrs={"num_filters": num_filters})
    return _add(ldef)


def img_pool(input, *, pool_size: Optional[int] = None, stride: int = 1,
             padding: int = 0, pool_type: str = "max-projection",
             name: str = None) -> LayerOutput:
    """pool_size=None pools over the full spatial extent (global pooling)."""
    src = _in(input)[0]
    if pool_size is None:
        info = _shape_of(src.name)
        extra = {"filter_size": info.width, "size_y": info.height,
                 "stride": info.width, "stride_y": info.height,
                 "padding": 0, "pool_type": pool_type}
        ldef = LayerDef(name=name or _auto_name("pool"), type="pool",
                        bias=False, inputs=[Input(src.name, extra=extra)])
        return _add(ldef)
    extra = {"filter_size": pool_size, "stride": stride, "padding": padding,
             "pool_type": pool_type}
    ldef = LayerDef(name=name or _auto_name("pool"), type="pool", bias=False,
                    inputs=[Input(src.name, extra=extra)])
    return _add(ldef)


def batch_norm(input, *, act: str = "linear", name: str = None,
               use_global_stats: bool = None,
               moving_average_fraction: float = 0.9,
               epsilon: float = 1e-5, bias_attr=True) -> LayerOutput:
    src = _in(input)[0]
    ldef = LayerDef(name=name or _auto_name("batch_norm"), type="batch_norm",
                    inputs=[Input(src.name)], act=act, bias=_bias(bias_attr),
                    attrs={"use_global_stats": use_global_stats,
                           "moving_average_fraction": moving_average_fraction,
                           "epsilon": epsilon})
    return _add(ldef)


def img_cmrnorm(input, *, size: int = 5, scale: float = 1e-4,
                power: float = 0.75, name: str = None) -> LayerOutput:
    src = _in(input)[0]
    ldef = LayerDef(name=name or _auto_name("norm"), type="norm", bias=False,
                    inputs=[Input(src.name, extra={"size": size,
                                                   "scale": scale,
                                                   "pow": power})])
    return _add(ldef)


def addto(inputs, *, act: str = "linear", name: str = None,
          bias_attr=False) -> LayerOutput:
    ldef = LayerDef(name=name or _auto_name("addto"), type="addto",
                    inputs=[Input(i.name) for i in _in(inputs)], act=act,
                    bias=_bias(bias_attr))
    return _add(ldef)


def concat(inputs, *, name: str = None, act: str = "linear") -> LayerOutput:
    ldef = LayerDef(name=name or _auto_name("concat"), type="concat",
                    inputs=[Input(i.name) for i in _in(inputs)], act=act,
                    bias=False)
    return _add(ldef)


def dropout(input, rate: float, *, name: str = None) -> LayerOutput:
    """Reference expresses dropout as a layer attr; standalone helper adds
    an identity addto carrying drop_rate."""
    src = _in(input)[0]
    ldef = LayerDef(name=name or _auto_name("dropout"), type="addto",
                    inputs=[Input(src.name)], bias=False, drop_rate=rate)
    return _add(ldef)


def lstmemory(input, *, name: str = None, reverse: bool = False,
              act: str = "tanh", gate_act: str = "sigmoid",
              state_act: str = "tanh", bias_attr=True,
              param_attr=None) -> LayerOutput:
    src = _in(input)[0]
    ldef = LayerDef(name=name or _auto_name("lstmemory"), type="lstmemory",
                    inputs=[Input(src.name, param_attr=_param(param_attr))],
                    bias=_bias(bias_attr),
                    attrs={"reversed": reverse, "active_type": act,
                           "active_gate_type": gate_act,
                           "active_state_type": state_act})
    return _add(ldef)


def grumemory(input, *, name: str = None, reverse: bool = False,
              act: str = "tanh", gate_act: str = "sigmoid",
              bias_attr=True, param_attr=None) -> LayerOutput:
    src = _in(input)[0]
    ldef = LayerDef(name=name or _auto_name("gru"), type="gated_recurrent",
                    inputs=[Input(src.name, param_attr=_param(param_attr))],
                    bias=_bias(bias_attr),
                    attrs={"reversed": reverse, "active_type": act,
                           "active_gate_type": gate_act})
    return _add(ldef)


def recurrent(input, *, name: str = None, reverse: bool = False,
              act: str = "tanh", bias_attr=True, param_attr=None) -> LayerOutput:
    src = _in(input)[0]
    ldef = LayerDef(name=name or _auto_name("recurrent"), type="recurrent",
                    inputs=[Input(src.name, param_attr=_param(param_attr))],
                    bias=_bias(bias_attr), act="linear",
                    attrs={"reversed": reverse, "active_type": act})
    return _add(ldef)


_POOL_TYPES = {"max": "max", "avg": "average", "average": "average",
               "sum": "average", "sqrt": "average", "last": "seqlastins",
               "first": "seqlastins"}


def pooling(input, *, pooling_type: str = "max", name: str = None) -> LayerOutput:
    """Sequence pooling (``pooling_layer`` in the reference DSL)."""
    src = _in(input)[0]
    ltype = _POOL_TYPES[pooling_type]
    attrs = {}
    if pooling_type == "sum":
        attrs["average_strategy"] = "sum"
    if pooling_type == "sqrt":
        attrs["average_strategy"] = "squarerootn"
    if pooling_type == "first":
        attrs["select_first"] = True
    ldef = LayerDef(name=name or _auto_name(f"seq_{pooling_type}"),
                    type=ltype, inputs=[Input(src.name)], bias=False,
                    attrs=attrs)
    return _add(ldef)


def last_seq(input, **kw):
    return pooling(input, pooling_type="last", **kw)


def first_seq(input, **kw):
    return pooling(input, pooling_type="first", **kw)


def expand(input, expand_as, *, name: str = None) -> LayerOutput:
    ldef = LayerDef(name=name or _auto_name("expand"), type="expand",
                    inputs=[Input(_in(input)[0].name),
                            Input(_in(expand_as)[0].name)], bias=False)
    return _add(ldef)


def maxid(input, *, name: str = None) -> LayerOutput:
    ldef = LayerDef(name=name or _auto_name("maxid"), type="maxid",
                    inputs=[Input(_in(input)[0].name)], bias=False)
    return _add(ldef)


def cos_sim(a, b, *, scale: float = 1.0, name: str = None) -> LayerOutput:
    ldef = LayerDef(name=name or _auto_name("cos"), type="cos",
                    inputs=[Input(_in(a)[0].name), Input(_in(b)[0].name)],
                    bias=False, attrs={"cos_scale": scale})
    return _add(ldef)


# ------------------------------------------------------------------ costs
def classification_cost(input, label, *, name: str = None) -> LayerOutput:
    """Cross-entropy on post-softmax input (the reference's
    ``classification_cost`` attaches a classification-error evaluator too —
    the trainer does that by layer type)."""
    ldef = LayerDef(name=name or _auto_name("cost"),
                    type="multi-class-cross-entropy",
                    inputs=[Input(_in(input)[0].name),
                            Input(_in(label)[0].name)], bias=False)
    return _add(ldef)


cross_entropy_cost = classification_cost


def square_error_cost(input, label, *, name: str = None) -> LayerOutput:
    ldef = LayerDef(name=name or _auto_name("cost"), type="square_error",
                    inputs=[Input(_in(input)[0].name),
                            Input(_in(label)[0].name)], bias=False)
    return _add(ldef)


mse_cost = square_error_cost


def rank_cost(left, right, label, *, name: str = None) -> LayerOutput:
    ldef = LayerDef(name=name or _auto_name("cost"), type="rank-cost",
                    inputs=[Input(_in(left)[0].name),
                            Input(_in(right)[0].name),
                            Input(_in(label)[0].name)], bias=False)
    return _add(ldef)


# ---------------------------------------------------------------- helpers
def _bias(bias_attr):
    if bias_attr is True or bias_attr is None:
        return True
    if bias_attr is False:
        return False
    return _param(bias_attr) or True


def _layer_attr(layer_attr: Optional[dict]):
    out = {}
    if layer_attr:
        if "drop_rate" in layer_attr:
            out["drop_rate"] = layer_attr["drop_rate"]
    return out
