"""Layer-construction DSL — the user API for building model graphs.

Role of ``python/paddle/trainer_config_helpers/layers.py`` (the v1 DSL) and
``python/paddle/v2/layer.py`` (its v2 graph-object wrapper): each function
appends a ``LayerDef`` to the active ``ModelDef`` and returns a
``LayerOutput`` handle usable as ``input=`` of later calls. Auto-generated
names follow the reference convention (``__fc_layer_0__``).

Unlike the reference there is no protobuf round-trip: the ModelDef *is* the
config; ``Topology``/``Network`` consume it directly.
"""

from __future__ import annotations

import contextlib
import dataclasses
import itertools
from typing import Any, Dict, List, Optional, Sequence, Union

from paddle_tpu.config.model_config import (Input, LayerDef, ModelDef,
                                            ParamAttr)

_GRAPH = ModelDef()
_COUNTERS: Dict[str, itertools.count] = {}


# modules holding per-build state keyed to this graph (e.g. the compat
# layer helpers' implicit ConfigContext) register a hook so reset() clears
# them too — names/counters must not leak across rebuilds
_RESET_HOOKS = []


def on_reset(fn):
    _RESET_HOOKS.append(fn)
    return fn


def reset():
    """Start a fresh graph (the reference resets config_parser globals per
    parse_config call)."""
    global _GRAPH, _COUNTERS, _GROUP_CTX, _DEVICE_SCOPE
    _GRAPH = ModelDef()
    _COUNTERS = {}
    _SHAPES.clear()
    # a build that raised inside a recurrent_group step must not leave the
    # group context armed for the next build (nor a pipeline_stage scope)
    _GROUP_CTX = None
    _DEVICE_SCOPE = None
    for fn in _RESET_HOOKS:
        fn()


def current_graph() -> ModelDef:
    return _GRAPH


def _auto_name(type_name: str) -> str:
    c = _COUNTERS.setdefault(type_name, itertools.count())
    return f"__{type_name}_layer_{next(c)}__"


@dataclasses.dataclass(frozen=True)
class LayerOutput:
    name: str
    size: int
    # the graph this layer belongs to, so consumers (Inference, Topology)
    # keep working after dsl.reset() starts a new one
    graph: Any = dataclasses.field(default=None, repr=False, compare=False)

    def __repr__(self):
        return f"LayerOutput({self.name!r}, size={self.size})"


def _in(x) -> List[LayerOutput]:
    if isinstance(x, LayerOutput):
        return [x]
    return list(x)


def _add(ldef: LayerDef) -> LayerOutput:
    if (_DEVICE_SCOPE is not None and ldef.type != "data"
            and ldef.attrs.get("device") is None):
        # pipeline_stage(s) scope: the --parallel_nn placement spelling
        ldef.attrs["device"] = _DEVICE_SCOPE
    _GRAPH.add(ldef)
    from paddle_tpu.core.registry import get_layer_impl
    # resolve output size via the impl's shape inference
    net_order = [i.layer_name for i in ldef.inputs]
    infos = []
    for n in net_order:
        infos.append(_shape_of(n))
    info = get_layer_impl(ldef.type).infer(ldef, infos)
    _SHAPES[ldef.name] = info
    return LayerOutput(ldef.name, info.size, graph=_GRAPH)


_SHAPES: Dict[str, Any] = {}


def _shape_of(name: str):
    return _SHAPES[name]


def _param(attr) -> Optional[ParamAttr]:
    if attr is None or isinstance(attr, ParamAttr):
        return attr
    if isinstance(attr, dict):
        return ParamAttr(**attr)
    raise TypeError(f"bad param attr {attr!r}")


# ----------------------------------------------------------------- layers
def data(name: str, size: int, *, height: int = None, width: int = None,
         channels: int = None, is_sequence: bool = False) -> LayerOutput:
    ldef = LayerDef(name=name, type="data", size=size, bias=False,
                    attrs={"height": height, "width": width,
                           "channels": channels, "is_sequence": is_sequence})
    return _add(ldef)


def fc(input, size: int, *, act: str = "tanh", name: str = None,
       bias_attr=True, param_attr=None, layer_attr: dict = None) -> LayerOutput:
    ins = [Input(i.name, param_attr=_param(param_attr)) for i in _in(input)]
    ldef = LayerDef(name=name or _auto_name("fc"), type="fc", inputs=ins,
                    size=size, act=act, bias=_bias(bias_attr),
                    **_layer_attr(layer_attr))
    return _add(ldef)


def moe(input, *, expert_hidden: int, num_experts: int,
        capacity: int = None, name: str = None) -> LayerOutput:
    """Top-1 mixture-of-experts FFN (TPU-native capability-add; output
    size = input size). Expert weights are ordinary parameters —
    shard them over the model axis via shard_rules for expert
    parallelism (`parallel/moe.py` documents the shard_map form)."""
    src = _in(input)[0]
    ldef = LayerDef(name=name or _auto_name("moe"), type="moe",
                    inputs=[Input(src.name)], bias=False,
                    attrs={"num_experts": num_experts,
                           "expert_hidden": expert_hidden,
                           "capacity": capacity})
    return _add(ldef)


def embedding(input, size: int, *, vocab_size: int = None, name: str = None,
              param_attr=None) -> LayerOutput:
    src = _in(input)[0]
    vocab = vocab_size or _shape_of(src.name).size
    ldef = LayerDef(name=name or _auto_name("embedding"), type="embedding",
                    inputs=[Input(src.name, param_attr=_param(param_attr))],
                    size=size, bias=False, attrs={"vocab_size": vocab})
    return _add(ldef)


def mixed(inputs: Sequence, size: int, *, projections: Sequence[dict],
          act: str = "linear", name: str = None, bias_attr=False) -> LayerOutput:
    ins = [Input(i.name, param_attr=_param(p.pop("param_attr", None)))
           for i, p in zip(_in(inputs), [dict(p) for p in projections])]
    ldef = LayerDef(name=name or _auto_name("mixed"), type="mixed",
                    inputs=ins, size=size, act=act, bias=_bias(bias_attr),
                    attrs={"projections": list(projections)})
    return _add(ldef)


def conv(input, *, num_filters: int, filter_size: int, stride: int = 1,
         padding: int = 0, groups: int = 1, channels: int = None,
         act: str = "relu", name: str = None, bias_attr=True,
         param_attr=None, layer_type: str = "exconv") -> LayerOutput:
    src = _in(input)[0]
    extra = {"filter_size": filter_size, "stride": stride,
             "padding": padding, "groups": groups}
    if channels:
        extra["channels"] = channels
    ldef = LayerDef(name=name or _auto_name("conv"), type=layer_type,
                    inputs=[Input(src.name, param_attr=_param(param_attr),
                                  extra=extra)],
                    act=act, bias=_bias(bias_attr),
                    attrs={"num_filters": num_filters})
    return _add(ldef)


def img_pool(input, *, pool_size: Optional[int] = None, stride: int = 1,
             padding: int = 0, pool_type: str = "max-projection",
             name: str = None) -> LayerOutput:
    """pool_size=None pools over the full spatial extent (global pooling)."""
    src = _in(input)[0]
    if pool_size is None:
        info = _shape_of(src.name)
        extra = {"filter_size": info.width, "size_y": info.height,
                 "stride": info.width, "stride_y": info.height,
                 "padding": 0, "pool_type": pool_type}
        ldef = LayerDef(name=name or _auto_name("pool"), type="pool",
                        bias=False, inputs=[Input(src.name, extra=extra)])
        return _add(ldef)
    extra = {"filter_size": pool_size, "stride": stride, "padding": padding,
             "pool_type": pool_type}
    ldef = LayerDef(name=name or _auto_name("pool"), type="pool", bias=False,
                    inputs=[Input(src.name, extra=extra)])
    return _add(ldef)


def batch_norm(input, *, act: str = "linear", name: str = None,
               use_global_stats: bool = None,
               moving_average_fraction: float = 0.9,
               epsilon: float = 1e-5, bias_attr=True,
               layer_attr: dict = None) -> LayerOutput:
    src = _in(input)[0]
    attrs = {"use_global_stats": use_global_stats,
             "moving_average_fraction": moving_average_fraction,
             "epsilon": epsilon}
    attrs.update(_layer_attr(layer_attr).get("attrs", {}))
    ldef = LayerDef(name=name or _auto_name("batch_norm"), type="batch_norm",
                    inputs=[Input(src.name)], act=act, bias=_bias(bias_attr),
                    attrs=attrs)
    return _add(ldef)


def img_cmrnorm(input, *, size: int = 5, scale: float = 1e-4,
                power: float = 0.75, name: str = None) -> LayerOutput:
    src = _in(input)[0]
    ldef = LayerDef(name=name or _auto_name("norm"), type="norm", bias=False,
                    inputs=[Input(src.name, extra={"size": size,
                                                   "scale": scale,
                                                   "pow": power})])
    return _add(ldef)


def addto(inputs, *, act: str = "linear", name: str = None,
          bias_attr=False) -> LayerOutput:
    ldef = LayerDef(name=name or _auto_name("addto"), type="addto",
                    inputs=[Input(i.name) for i in _in(inputs)], act=act,
                    bias=_bias(bias_attr))
    return _add(ldef)


def concat(inputs, *, name: str = None, act: str = "linear") -> LayerOutput:
    ldef = LayerDef(name=name or _auto_name("concat"), type="concat",
                    inputs=[Input(i.name) for i in _in(inputs)], act=act,
                    bias=False)
    return _add(ldef)


def dropout(input, rate: float, *, name: str = None) -> LayerOutput:
    """Reference expresses dropout as a layer attr; standalone helper adds
    an identity addto carrying drop_rate."""
    src = _in(input)[0]
    ldef = LayerDef(name=name or _auto_name("dropout"), type="addto",
                    inputs=[Input(src.name)], bias=False, drop_rate=rate)
    return _add(ldef)


def lstmemory(input, *, name: str = None, reverse: bool = False,
              act: str = "tanh", gate_act: str = "sigmoid",
              state_act: str = "tanh", bias_attr=True,
              param_attr=None) -> LayerOutput:
    src = _in(input)[0]
    ldef = LayerDef(name=name or _auto_name("lstmemory"), type="lstmemory",
                    inputs=[Input(src.name, param_attr=_param(param_attr))],
                    bias=_bias(bias_attr),
                    attrs={"reversed": reverse, "active_type": act,
                           "active_gate_type": gate_act,
                           "active_state_type": state_act})
    return _add(ldef)


def grumemory(input, *, name: str = None, reverse: bool = False,
              act: str = "tanh", gate_act: str = "sigmoid",
              bias_attr=True, param_attr=None) -> LayerOutput:
    src = _in(input)[0]
    ldef = LayerDef(name=name or _auto_name("gru"), type="gated_recurrent",
                    inputs=[Input(src.name, param_attr=_param(param_attr))],
                    bias=_bias(bias_attr),
                    attrs={"reversed": reverse, "active_type": act,
                           "active_gate_type": gate_act})
    return _add(ldef)


def multi_head_attention(query, key_value=None, *, size: int = None,
                         num_heads: int = 1, causal: bool = False,
                         seq_parallel: str = None, seq_axis: str = "seq",
                         name: str = None, bias_attr=True,
                         param_attr=None) -> LayerOutput:
    """Fused multi-head attention (flash kernel on TPU); self-attention
    when key_value is omitted. Capability-add over the reference's
    composite simple_attention.

    ``seq_parallel="ring"|"ulysses"`` turns on sequence parallelism for
    long contexts: when the trainer runs with a mesh carrying
    ``seq_axis`` (``create_mesh(n_seq=...)``), the attention shards the
    time dimension over it (ring = KV rotation over ICI, ulysses =
    heads<->sequence all-to-all; ulysses needs num_heads divisible by
    the axis size). Without such a mesh the layer runs dense."""
    q = _in(query)[0]
    inputs = [Input(q.name, param_attr=_param(param_attr))]
    if key_value is not None:
        inputs.append(Input(_in(key_value)[0].name))
    if seq_parallel not in (None, "ring", "ulysses"):
        raise ValueError(f"seq_parallel must be ring/ulysses, "
                         f"got {seq_parallel!r}")
    ldef = LayerDef(name=name or _auto_name("mha"),
                    type="multi_head_attention", inputs=inputs,
                    size=size or q.size, act="linear",
                    bias=_bias(bias_attr),
                    attrs={"num_heads": num_heads, "causal": causal,
                           "seq_parallel": seq_parallel,
                           "seq_axis": seq_axis})
    return _add(ldef)


def recurrent(input, *, name: str = None, reverse: bool = False,
              act: str = "tanh", bias_attr=True, param_attr=None) -> LayerOutput:
    src = _in(input)[0]
    ldef = LayerDef(name=name or _auto_name("recurrent"), type="recurrent",
                    inputs=[Input(src.name, param_attr=_param(param_attr))],
                    bias=_bias(bias_attr), act="linear",
                    attrs={"reversed": reverse, "active_type": act})
    return _add(ldef)


_POOL_TYPES = {"max": "max", "avg": "average", "average": "average",
               "sum": "average", "sqrt": "average", "last": "seqlastins",
               "first": "seqlastins"}


def pooling(input, *, pooling_type: str = "max", name: str = None) -> LayerOutput:
    """Sequence pooling (``pooling_layer`` in the reference DSL)."""
    src = _in(input)[0]
    ltype = _POOL_TYPES[pooling_type]
    attrs = {}
    if pooling_type == "sum":
        attrs["average_strategy"] = "sum"
    if pooling_type == "sqrt":
        attrs["average_strategy"] = "squarerootn"
    if pooling_type == "first":
        attrs["select_first"] = True
    ldef = LayerDef(name=name or _auto_name(f"seq_{pooling_type}"),
                    type=ltype, inputs=[Input(src.name)], bias=False,
                    attrs=attrs)
    return _add(ldef)


def last_seq(input, **kw):
    return pooling(input, pooling_type="last", **kw)


def first_seq(input, **kw):
    return pooling(input, pooling_type="first", **kw)


def expand(input, expand_as, *, name: str = None) -> LayerOutput:
    ldef = LayerDef(name=name or _auto_name("expand"), type="expand",
                    inputs=[Input(_in(input)[0].name),
                            Input(_in(expand_as)[0].name)], bias=False)
    return _add(ldef)


def maxid(input, *, name: str = None) -> LayerOutput:
    ldef = LayerDef(name=name or _auto_name("maxid"), type="maxid",
                    inputs=[Input(_in(input)[0].name)], bias=False)
    return _add(ldef)


def cos_sim(a, b, *, scale: float = 1.0, name: str = None) -> LayerOutput:
    ldef = LayerDef(name=name or _auto_name("cos"), type="cos",
                    inputs=[Input(_in(a)[0].name), Input(_in(b)[0].name)],
                    bias=False, attrs={"cos_scale": scale})
    return _add(ldef)


# ------------------------------------------------------------------ costs
def classification_cost(input, label, *, name: str = None) -> LayerOutput:
    """Cross-entropy on post-softmax input (the reference's
    ``classification_cost`` attaches a classification-error evaluator too —
    the trainer does that by layer type)."""
    ldef = LayerDef(name=name or _auto_name("cost"),
                    type="multi-class-cross-entropy",
                    inputs=[Input(_in(input)[0].name),
                            Input(_in(label)[0].name)], bias=False)
    return _add(ldef)


cross_entropy_cost = classification_cost


def square_error_cost(input, label, *, name: str = None) -> LayerOutput:
    ldef = LayerDef(name=name or _auto_name("cost"), type="square_error",
                    inputs=[Input(_in(input)[0].name),
                            Input(_in(label)[0].name)], bias=False)
    return _add(ldef)


mse_cost = square_error_cost


def rank_cost(left, right, label, *, name: str = None) -> LayerOutput:
    ldef = LayerDef(name=name or _auto_name("cost"), type="rank-cost",
                    inputs=[Input(_in(left)[0].name),
                            Input(_in(right)[0].name),
                            Input(_in(label)[0].name)], bias=False)
    return _add(ldef)


# ---------------------------------------------------------------- helpers
def _bias(bias_attr):
    if bias_attr is True or bias_attr is None:
        return True
    if bias_attr is False:
        return False
    return _param(bias_attr) or True


def _layer_attr(layer_attr: Optional[dict]):
    out = {}
    if layer_attr:
        if "drop_rate" in layer_attr:
            out["drop_rate"] = layer_attr["drop_rate"]
        attrs = {}
        if "device" in layer_attr:
            # per-layer placement (--parallel_nn); consumed by
            # parallel.mesh.device_attr_rules as a model-axis shard hint
            # or, all-layers-contiguous, as GPipe stage ids
            # (parallel/pipeline.py)
            attrs["device"] = layer_attr["device"]
        if "recompute" in layer_attr:
            # per-layer rematerialization (jax.checkpoint in the executor)
            attrs["recompute"] = bool(layer_attr["recompute"])
        if attrs:
            out["attrs"] = attrs
    return out


_DEVICE_SCOPE: Optional[int] = None


@contextlib.contextmanager
def pipeline_stage(stage: int):
    """``with dsl.pipeline_stage(s): ...`` — every non-data layer built
    inside carries ``device=s``, the reference's ``--parallel_nn``
    placement spelling (``ParallelNeuralNetwork.h:23-62``) without
    repeating ``layer_attr={"device": s}`` per layer. An explicit
    per-layer ``device`` wins; scopes nest (innermost wins). Contiguous
    stage ids 0..S-1 across the body make the config trainable through
    ``SGD.train(pipeline=True)`` / ``--parallel_nn``
    (``docs/pipeline_parallel.md``)."""
    global _DEVICE_SCOPE
    prev = _DEVICE_SCOPE
    _DEVICE_SCOPE = int(stage)
    try:
        yield
    finally:
        _DEVICE_SCOPE = prev


# ------------------------------------------------- recurrent groups (§3.5)
@dataclasses.dataclass
class StaticInput:
    """Non-time-varying input to a recurrent_group (the reference's
    StaticInput: read whole each timestep, not sliced)."""

    input: LayerOutput


@dataclasses.dataclass
class SubsequenceInput:
    """Two-level (nested) sequence input to a recurrent_group: the outer
    group steps over SUB-sequences; each step sees one whole sub-sequence
    as a sequence Argument (the reference's SubsequenceInput +
    ``RecurrentGradientMachine`` nested frames, ``:294-346``). Nested
    batches flow as [B, S, T_sub, D] with mask [B, S, T_sub] — the padded
    static-shape spelling of ``subSequenceStartPositions``."""

    input: LayerOutput


@dataclasses.dataclass
class GeneratedInput:
    """Generation-mode input: at each step the previous step's generated
    word id is embedded and fed (reference GeneratedInput in
    trainer_config_helpers/layers.py; consumed by beam search,
    RecurrentGradientMachine.cpp:964+)."""

    size: int                      # vocabulary size
    embedding_name: str            # shared embedding parameter name
    embedding_size: int
    bos_id: int = 0
    eos_id: int = 1


_GROUP_CTX: Optional[Dict[str, Any]] = None


def memory(*, name: str, size: int, boot_layer: Optional[LayerOutput] = None,
           boot_with_const_value: float = 0.0,
           agent_name: Optional[str] = None) -> LayerOutput:
    """Declare a recurrent memory inside a recurrent_group step function:
    the previous timestep's output of the layer called ``name`` (zero /
    constant / boot-layer initialized). Mirrors the DSL ``memory()`` that
    becomes an in_link on the reference's recurrent sub-model."""
    global _GROUP_CTX
    if _GROUP_CTX is None:
        raise RuntimeError(
            "memory() must be called inside a recurrent_group step function")
    if name is None:
        # anonymous memory: the link target is bound later via
        # .set_input(layer) (the reference DSL's memory.set_input)
        name = f"__anon_mem_{len(_GROUP_CTX['memories'])}__"
    bname = f"{_GROUP_CTX['name']}@mem_{name}"
    out = _add(LayerDef(name=bname, type="data", size=size, bias=False))
    _GROUP_CTX["memories"].append(
        {"boundary": bname, "link": name, "boot_layer": boot_layer,
         "init": boot_with_const_value, "agent_name": agent_name})
    return out


def _memory_set_input(self, layer):
    """The reference DSL's ``memory.set_input``: bind an anonymous memory
    to its producing layer after the fact."""
    if _GROUP_CTX is not None:
        for entry in _GROUP_CTX["memories"]:
            if entry["boundary"] == self.name:
                entry["link"] = layer.name
                return
    raise RuntimeError("set_input() is only valid on a memory created "
                       "inside the active recurrent_group")


LayerOutput.set_input = _memory_set_input


def recurrent_group(step, input, *, reverse: bool = False,
                    name: str = None, target_inlink=None):
    """Unroll a user step network over the timesteps of the sequence
    inputs (the TPU-native ``RecurrentGradientMachine`` training path —
    see paddle_tpu/layers/group.py). ``input`` items: sequence
    LayerOutputs (sliced per step), StaticInput (whole every step).
    The step function may call memory() and returns one LayerOutput or a
    tuple (first = main out_link)."""
    global _GRAPH, _GROUP_CTX
    from paddle_tpu.config.model_config import ModelDef as _ModelDef
    inputs = [input] if isinstance(
        input, (LayerOutput, StaticInput, SubsequenceInput)) else list(input)
    # reference auto-name convention: __recurrent_group_0__ (config_parser
    # RecurrentLayerGroupBegin), not the generic __X_layer_0__ pattern
    c = _COUNTERS.setdefault("recurrent_group", itertools.count())
    gname = name or f"__recurrent_group_{next(c)}__"
    outer = _GRAPH
    sub = _ModelDef()
    ins_meta: List[Dict[str, Any]] = []
    outer_in_names: List[str] = []
    proxies: List[LayerOutput] = []
    prev_ctx = _GROUP_CTX
    _GRAPH = sub
    _GROUP_CTX = {"name": gname, "memories": []}
    try:
        for i, x in enumerate(inputs):
            if isinstance(x, StaticInput):
                src = x.input
                bname = f"{gname}@static{i}"
                kind = "static"
                ldef = LayerDef(name=bname, type="data", size=src.size,
                                bias=False)
            elif isinstance(x, SubsequenceInput):
                # outer step sees one whole sub-sequence: the boundary
                # data layer is itself a sequence inside the step net
                src = x.input
                bname = f"{gname}@subseq{i}"
                kind = "subseq"
                ldef = LayerDef(name=bname, type="data", size=src.size,
                                bias=False,
                                attrs={"is_sequence": True})
            else:
                src = x
                bname = f"{gname}@seq{i}"
                # a plain input whose source the graph KNOWS is a
                # sequence steps per timestep; otherwise the level is
                # only knowable from the fed data (the reference infers
                # it from the provider's slot types), so defer to the
                # executor's runtime resolution ("auto": 3-D mask ->
                # sub-sequence, maskless flat -> static broadcast)
                try:
                    is_seq = _shape_of(src.name).is_sequence
                except KeyError:
                    is_seq = False
                kind = "seq" if is_seq else "auto"
                # NOTE: the boundary stays a plain (non-sequence) data
                # layer even for kind="seq" — the step sees ONE frame
                # per timestep, not a sequence
                ldef = LayerDef(name=bname, type="data", size=src.size,
                                bias=False)
            proxies.append(_add(ldef))
            ins_meta.append({"boundary": bname, "kind": kind})
            outer_in_names.append(src.name)
        traced = step(*proxies)
        memories = _GROUP_CTX["memories"]
    finally:
        _GRAPH = outer
        _GROUP_CTX = prev_ctx

    out_handles = list(traced) if isinstance(traced, (tuple, list)) \
        else [traced]
    for mem in memories:
        if mem["link"] not in sub.layers:
            raise ValueError(
                f"memory(name={mem['link']!r}) has no matching layer "
                f"inside recurrent_group {gname!r}")
        bl = mem.pop("boot_layer")
        if bl is not None:
            ins_meta.append({"boundary": mem["boundary"], "kind": "boot"})
            outer_in_names.append(bl.name)
    # targetInlink (config_parser target_inlinkname): which in-link's
    # sub-sequence boundaries define the group's OUTPUT structure
    target_idx = 0
    if target_inlink is not None:
        for i, x in enumerate(inputs):
            src_in = getattr(x, "input", x)
            if getattr(src_in, "name", None) == target_inlink.name:
                target_idx = i
                break
    ldef = LayerDef(
        name=gname, type="recurrent_layer_group",
        inputs=[Input(n) for n in outer_in_names], bias=False,
        attrs={"sub_model": sub, "ins": ins_meta, "memories": memories,
               "outputs": [h.name for h in out_handles],
               "reverse": reverse,
               "target_boundary": ins_meta[target_idx]["boundary"]})
    main = _add(ldef)
    if len(out_handles) == 1:
        return main
    extras = []
    for h in out_handles[1:]:
        odef = LayerDef(name=f"{gname}@out_{h.name}", type="group_output",
                        inputs=[Input(main.name)], size=h.size, bias=False,
                        attrs={"sub_name": h.name})
        extras.append(_add(odef))
    return (main, *extras)



def evaluator(type: str, input, *, label=None, weight=None, name: str = None,
              **kwargs):
    """Attach a metric evaluator to the graph (the native spelling of the
    reference's evaluator config funcs, `trainer_config_helpers/
    evaluators.py`); the trainer wires it to the metric registry
    (paddle_tpu/trainer/metrics.py) each pass."""
    ins = [input] if isinstance(input, LayerOutput) else list(input)
    names = [i.name for i in ins]
    n_outputs = len(names)
    for extra in (label, weight):
        if extra is not None:
            names.append(extra.name)
    cfg = {"type": type,
           "name": name or _auto_name(f"{type}_evaluator").replace(
               "_layer_", "_"),
           "input_layers": names,
           "_roles": {"n_outputs": n_outputs,
                      "has_label": label is not None,
                      "has_weight": weight is not None}}
    cfg.update({k: v for k, v in kwargs.items() if v is not None})
    current_graph().evaluators.append(cfg)
    return cfg

def slope_intercept(input, *, slope: float = 1.0, intercept: float = 0.0,
                    name: str = None) -> LayerOutput:
    ldef = LayerDef(name=name or _auto_name("slope_intercept"),
                    type="slope_intercept", inputs=[Input(_in(input)[0].name)],
                    bias=False, attrs={"slope": slope, "intercept": intercept})
    return _add(ldef)


def beam_search(step, input, *, bos_id: int = None, eos_id: int = None,
                beam_size: int = 5, max_length: int = 100,
                candidate_adjust=None, drop_callback=None,
                norm_or_drop=None, stop_beam_search=None,
                decode_chunk: int = None, full_scan: bool = False,
                name: str = None) -> LayerOutput:
    """Generation-mode recurrent group (``beam_search`` in the reference
    DSL; executed by ``RecurrentGradientMachine::generateSequence``). The
    step function receives the embedding of the previously generated word
    for the GeneratedInput slot and must return post-softmax probabilities
    over the vocabulary. Run it with
    ``paddle_tpu.core.generation.SequenceGenerator``.

    The four beam-control hooks (``candidate_adjust``, ``drop_callback``,
    ``norm_or_drop``, ``stop_beam_search`` —
    ``RecurrentGradientMachine.h:92-145``, signatures in
    ``core/generation.py:SequenceGenerator.generate``) pinned here become
    the defaults for every ``generate`` call on this config, including
    the SWIG surface and the serving generation endpoint. They are traced
    into the jitted search; use module-level functions (not lambdas) if
    the model will be merged for deployment (``--job=merge`` pickles the
    graph).

    ``decode_chunk`` / ``full_scan`` pin the early-exit decode policy
    (``docs/generation.md``): the search runs ``decode_chunk`` steps per
    compiled chunk and exits as soon as every beam finished (byte-
    identical to the full scan, cost proportional to actual output
    length); ``full_scan=True`` pins the single length-``max_length``
    scan."""
    global _GRAPH, _GROUP_CTX
    from paddle_tpu.config.model_config import ModelDef as _ModelDef
    inputs = list(input) if isinstance(input, (list, tuple)) else [input]
    gname = name or _auto_name("beam_search")
    outer = _GRAPH
    sub = _ModelDef()
    ins_meta: List[Dict[str, Any]] = []
    outer_in_names: List[str] = []
    proxies: List[LayerOutput] = []
    gen_spec = None
    prev_ctx = _GROUP_CTX
    _GRAPH = sub
    _GROUP_CTX = {"name": gname, "memories": []}
    try:
        for i, x in enumerate(inputs):
            if isinstance(x, GeneratedInput):
                if gen_spec is not None:
                    raise ValueError("only one GeneratedInput allowed")
                bname = f"{gname}@gen{i}"
                proxies.append(_add(LayerDef(
                    name=bname, type="data", size=x.embedding_size,
                    bias=False)))
                gen_spec = {"boundary": bname, "size": x.size,
                            "embedding_name": x.embedding_name,
                            "embedding_size": x.embedding_size,
                            "bos_id": bos_id if bos_id is not None else x.bos_id,
                            "eos_id": eos_id if eos_id is not None else x.eos_id}
            elif isinstance(x, StaticInput):
                bname = f"{gname}@static{i}"
                proxies.append(_add(LayerDef(
                    name=bname, type="data", size=x.input.size, bias=False)))
                ins_meta.append({"boundary": bname, "kind": "static"})
                outer_in_names.append(x.input.name)
            else:
                raise TypeError(
                    "beam_search inputs must be GeneratedInput/StaticInput")
        traced = step(*proxies)
        memories = _GROUP_CTX["memories"]
    finally:
        _GRAPH = outer
        _GROUP_CTX = prev_ctx
    if gen_spec is None:
        raise ValueError("beam_search needs a GeneratedInput")
    out_handles = list(traced) if isinstance(traced, (tuple, list)) \
        else [traced]
    for mem in memories:
        if mem["link"] not in sub.layers:
            raise ValueError(
                f"memory(name={mem['link']!r}) has no matching layer "
                f"inside beam_search group {gname!r}")
        bl = mem.pop("boot_layer")
        if bl is not None:
            ins_meta.append({"boundary": mem["boundary"], "kind": "boot"})
            outer_in_names.append(bl.name)
    ldef = LayerDef(
        name=gname, type="beam_search_group",
        inputs=[Input(n) for n in outer_in_names], bias=False,
        attrs={"sub_model": sub, "ins": ins_meta, "memories": memories,
               "outputs": [h.name for h in out_handles], "gen": gen_spec,
               "beam_size": beam_size, "max_length": max_length,
               "candidate_adjust": candidate_adjust,
               "drop_callback": drop_callback,
               "norm_or_drop": norm_or_drop,
               "stop_beam_search": stop_beam_search,
               "decode_chunk": decode_chunk, "full_scan": full_scan})
    return _add(ldef)


def crf_layer(input, label, *, size: int = None, weight=None,
              param_attr=None, name: str = None) -> LayerOutput:
    ins = [Input(_in(input)[0].name, param_attr=_param(param_attr)),
           Input(_in(label)[0].name)]
    if weight is not None:
        ins.append(Input(_in(weight)[0].name))
    ldef = LayerDef(name=name or _auto_name("crf"), type="crf",
                    inputs=ins, bias=False)
    return _add(ldef)


def crf_decoding_layer(input, *, size: int = None, label=None,
                       param_attr=None, name: str = None) -> LayerOutput:
    ins = [Input(_in(input)[0].name, param_attr=_param(param_attr))]
    if label is not None:
        ins.append(Input(_in(label)[0].name))
    ldef = LayerDef(name=name or _auto_name("crf_decoding"),
                    type="crf_decoding", inputs=ins, bias=False)
    return _add(ldef)


def ctc_layer(input, label, *, size: int = None, norm_by_times: bool = False,
              blank: int = None, name: str = None) -> LayerOutput:
    attrs = {"norm_by_times": norm_by_times}
    if blank is not None:
        attrs["blank"] = blank
    ldef = LayerDef(name=name or _auto_name("ctc"), type="ctc",
                    inputs=[Input(_in(input)[0].name),
                            Input(_in(label)[0].name)],
                    bias=False, attrs=attrs)
    return _add(ldef)


def warp_ctc_layer(input, label, *, size: int = None,
                   norm_by_times: bool = False, blank: int = 0,
                   name: str = None) -> LayerOutput:
    ldef = LayerDef(name=name or _auto_name("warp_ctc"), type="warp_ctc",
                    inputs=[Input(_in(input)[0].name),
                            Input(_in(label)[0].name)],
                    bias=False,
                    attrs={"norm_by_times": norm_by_times, "blank": blank})
    return _add(ldef)


# ------------------------------------------------ long-tail layer wrappers
def _simple(type_name, input, name=None, *, attrs=None, size=None,
            extra_inputs=(), act="linear", bias=False, param_attr=None):
    ins = [Input(_in(input)[0].name, param_attr=_param(param_attr))]
    ins += [Input(_in(e)[0].name) for e in extra_inputs]
    ldef = LayerDef(name=name or _auto_name(type_name), type=type_name,
                    inputs=ins, size=size, act=act, bias=bias,
                    attrs=attrs or {})
    return _add(ldef)


def clip_layer(input, *, min: float, max: float, name=None):
    return _simple("clip", input, name, attrs={"min": min, "max": max})


def scaling_layer(input, weight, *, name=None):
    """Row-wise scale: out[i] = weight[i] * input[i] (weight is [B, 1] or
    per-timestep [B, T, 1]); the attention-weighting primitive."""
    ldef = LayerDef(name=name or _auto_name("scaling"), type="scaling",
                    inputs=[Input(_in(weight)[0].name),
                            Input(_in(input)[0].name)], bias=False)
    return _add(ldef)


def power_layer(input, weight, *, name=None):
    ldef = LayerDef(name=name or _auto_name("power"), type="power",
                    inputs=[Input(_in(weight)[0].name),
                            Input(_in(input)[0].name)], bias=False)
    return _add(ldef)


def prelu_layer(input, *, partial_sum: int = 1, name=None, param_attr=None):
    return _simple("prelu", input, name, attrs={"partial_sum": partial_sum},
                   param_attr=param_attr)


def maxout_layer(input, *, groups: int, name=None):
    return _simple("maxout", input, name, attrs={"groups": groups})


def multiplex_layer(index, inputs, *, name=None):
    ins = [Input(_in(index)[0].name)] + [Input(_in(i)[0].name)
                                         for i in inputs]
    return _add(LayerDef(name=name or _auto_name("multiplex"),
                         type="multiplex", inputs=ins, bias=False))


def eos_id_layer(input, *, eos_id: int, name=None):
    return _simple("eos_id", input, name, attrs={"eos_id": eos_id})


def sampling_id_layer(input, *, name=None):
    return _simple("sampling_id", input, name)


def print_layer(input, *, name=None):
    return _simple("print", input, name)


def resize_layer(input, *, size: int, name=None):
    return _simple("resize", input, name, size=size)


def rotate_layer(input, *, name=None):
    return _simple("rotate", input, name)


def bilinear_interp_layer(input, *, out_size_x: int, out_size_y: int,
                          name=None):
    return _simple("bilinear_interp", input, name,
                   attrs={"out_size_x": out_size_x, "out_size_y": out_size_y})


def pad_layer(input, *, pad_c=(0, 0), pad_h=(0, 0), pad_w=(0, 0), name=None):
    return _simple("pad", input, name,
                   attrs={"pad_c": list(pad_c), "pad_h": list(pad_h),
                          "pad_w": list(pad_w)})


def crop_layer(input, *, axis: int = 2, offset=None, shape=None,
               reference=None, name=None):
    attrs = {"axis": axis}
    if offset is not None:
        attrs["offset"] = list(offset)
    if shape is not None:
        attrs["shape"] = list(shape)
    extra = [reference] if reference is not None else []
    return _simple("crop", input, name, attrs=attrs, extra_inputs=extra)


def conv_shift_layer(a, b, *, name=None):
    ldef = LayerDef(name=name or _auto_name("conv_shift"), type="conv_shift",
                    inputs=[Input(_in(a)[0].name), Input(_in(b)[0].name)],
                    bias=False)
    return _add(ldef)


def row_conv_layer(input, *, context_length: int, name=None,
                   param_attr=None):
    return _simple("row_conv", input, name,
                   attrs={"context_length": context_length},
                   param_attr=param_attr)


def tensor_layer(a, b, *, size: int, act: str = "linear", name=None,
                 bias_attr=True, param_attr=None):
    ldef = LayerDef(name=name or _auto_name("tensor"), type="tensor",
                    inputs=[Input(_in(a)[0].name, param_attr=_param(param_attr)),
                            Input(_in(b)[0].name)],
                    size=size, act=act, bias=_bias(bias_attr))
    return _add(ldef)


def selective_fc_layer(input, *, size: int, select=None, act: str = "tanh",
                       name=None, bias_attr=True, param_attr=None):
    # the layer consumes the activation itself (mask applied post-act)
    extra = [select] if select is not None else []
    return _simple("selective_fc", input, name, size=size, act="linear",
                   bias=_bias(bias_attr), extra_inputs=extra,
                   param_attr=param_attr, attrs={"active_type": act})


def mdlstm_layer(input, *, name=None, act: str = "tanh",
                 gate_act: str = "sigmoid", state_act: str = "tanh",
                 bias_attr=True, param_attr=None):
    """2-D multi-dimensional LSTM over an image-shaped gate projection
    (input channels = 5*size)."""
    return _simple("mdlstmemory", input, name, bias=_bias(bias_attr),
                   param_attr=param_attr,
                   attrs={"active_type": act, "active_gate_type": gate_act,
                          "active_state_type": state_act})


def block_expand_layer(input, *, block_x: int, block_y: int,
                       stride_x: int = 1, stride_y: int = 1,
                       padding_x: int = 0, padding_y: int = 0, name=None):
    return _simple("blockexpand", input, name,
                   attrs={"block_x": block_x, "block_y": block_y,
                          "stride_x": stride_x, "stride_y": stride_y,
                          "padding_x": padding_x, "padding_y": padding_y})


def sub_nested_seq_layer(input, selection, *, name=None):
    return _simple("sub_nested_seq", input, name, extra_inputs=[selection])


def get_output_layer(input, *, arg_name: str = "state", size: int = None,
                     name=None):
    return _simple("get_output", input, name, size=size,
                   attrs={"arg_name": arg_name})


def gru_step_layer(input, output_mem, *, size: int = None, act: str = "tanh",
                   gate_act: str = "sigmoid", name=None, bias_attr=True,
                   param_attr=None):
    ldef = LayerDef(name=name or _auto_name("gru_step"), type="gru_step",
                    inputs=[Input(_in(input)[0].name,
                                  param_attr=_param(param_attr)),
                            Input(_in(output_mem)[0].name)],
                    bias=_bias(bias_attr),
                    attrs={"active_type": act,
                           "active_gate_type": gate_act})
    return _add(ldef)


def lstm_step_layer(input, state_mem, *, size: int = None, act: str = "tanh",
                    gate_act: str = "sigmoid", state_act: str = "tanh",
                    name=None, bias_attr=True):
    ldef = LayerDef(name=name or _auto_name("lstm_step"), type="lstm_step",
                    inputs=[Input(_in(input)[0].name),
                            Input(_in(state_mem)[0].name)],
                    bias=_bias(bias_attr),
                    attrs={"active_type": act, "active_gate_type": gate_act,
                           "active_state_type": state_act})
    return _add(ldef)


def nce_layer(input, label, *, num_classes: int, num_neg_samples: int = 10,
              weight=None, name=None, bias_attr=True, param_attr=None):
    ins = [Input(_in(input)[0].name, param_attr=_param(param_attr)),
           Input(_in(label)[0].name)]
    if weight is not None:
        ins.append(Input(_in(weight)[0].name))
    ldef = LayerDef(name=name or _auto_name("nce"), type="nce", inputs=ins,
                    bias=_bias(bias_attr),
                    attrs={"num_classes": num_classes,
                           "num_neg_samples": num_neg_samples})
    return _add(ldef)


def hsigmoid(input, label, *, num_classes: int, name=None, bias_attr=True,
             param_attr=None):
    srcs = _in(input)
    ins = [Input(s.name, param_attr=_param(param_attr)) for s in srcs]
    ins.append(Input(_in(label)[0].name))
    ldef = LayerDef(name=name or _auto_name("hsigmoid"), type="hsigmoid",
                    inputs=ins, bias=_bias(bias_attr),
                    attrs={"num_classes": num_classes})
    return _add(ldef)


def priorbox_layer(input, image, *, min_size, max_size=(), aspect_ratio=(1.0,),
                   variance=(0.1, 0.1, 0.2, 0.2), name=None):
    ldef = LayerDef(name=name or _auto_name("priorbox"), type="priorbox",
                    inputs=[Input(_in(input)[0].name),
                            Input(_in(image)[0].name)], bias=False,
                    attrs={"min_size": list(min_size),
                           "max_size": list(max_size),
                           "aspect_ratio": list(aspect_ratio),
                           "variance": list(variance)})
    return _add(ldef)


def multibox_loss_layer(priorbox, label, conf, loc, *, num_classes: int,
                        overlap_threshold: float = 0.5,
                        neg_pos_ratio: float = 3.0, neg_overlap: float = 0.5,
                        background_id: int = 0, name=None):
    ldef = LayerDef(name=name or _auto_name("multibox_loss"),
                    type="multibox_loss",
                    inputs=[Input(_in(priorbox)[0].name),
                            Input(_in(label)[0].name),
                            Input(_in(loc)[0].name),
                            Input(_in(conf)[0].name)], bias=False,
                    attrs={"num_classes": num_classes,
                           "overlap_threshold": overlap_threshold,
                           "neg_pos_ratio": neg_pos_ratio,
                           "neg_overlap": neg_overlap,
                           "background_id": background_id})
    return _add(ldef)


def detection_output_layer(priorbox, conf, loc, *, num_classes: int,
                           nms_threshold: float = 0.45,
                           nms_top_k: int = 100, keep_top_k: int = 200,
                           confidence_threshold: float = 0.01,
                           background_id: int = 0, name=None):
    ldef = LayerDef(name=name or _auto_name("detection_output"),
                    type="detection_output",
                    inputs=[Input(_in(priorbox)[0].name),
                            Input(_in(loc)[0].name),
                            Input(_in(conf)[0].name)], bias=False,
                    attrs={"num_classes": num_classes,
                           "nms_threshold": nms_threshold,
                           "nms_top_k": nms_top_k, "keep_top_k": keep_top_k,
                           "confidence_threshold": confidence_threshold,
                           "background_id": background_id})
    return _add(ldef)
