// Native data runtime: chunked record IO + threaded prefetch pool.
//
// TPU-native equivalent of the reference's native data path:
//  - RecordIO-style chunk files (the Go master dispatches RecordIO chunks,
//    go/master/service.go:106; format re-designed, not copied: magic +
//    [len][crc32][payload] records, CRC-checked on read).
//  - DataProvider's async double-buffer prefetch (DataProvider.h:249,343):
//    a worker-thread pool reads chunk files into a bounded ring of
//    records, overlapping disk IO + deserialization with device compute.
//    Bounded queue <-> the reference's blocking Queue (utils/Queue.h).
//
// Exposed as a C ABI consumed via ctypes (paddle_tpu/data/native.py).
// Build: g++ -O2 -shared -fPIC (no external deps; crc32 implemented here).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

// ----------------------------------------------------------------- crc32
uint32_t crc_table[256];
struct CrcInit {
  CrcInit() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      crc_table[i] = c;
    }
  }
} crc_init;

uint32_t crc32(const uint8_t* buf, size_t len) {
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; i++)
    c = crc_table[(c ^ buf[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

const char kMagic[4] = {'P', 'T', 'R', '1'};

// ---------------------------------------------------------------- writer
struct Writer {
  FILE* f;
  std::string error;
};

// ---------------------------------------------------------------- reader
struct Reader {
  FILE* f;
  std::vector<uint8_t> buf;
  std::string error;
};

bool read_exact(FILE* f, void* dst, size_t n) {
  return fread(dst, 1, n, f) == n;
}

// ------------------------------------------------------------------ pool
struct Pool {
  std::vector<std::string> paths;
  size_t queue_cap;
  bool shuffle;
  uint64_t seed;
  int epoch_records = 0;

  std::deque<std::vector<uint8_t>> queue;
  std::mutex mu;
  std::condition_variable not_empty, not_full;
  std::atomic<bool> done{false}, stop{false};
  std::thread worker;
  std::string error;

  ~Pool() {
    stop.store(true);
    not_full.notify_all();
    not_empty.notify_all();
    if (worker.joinable()) worker.join();
  }
};

void pool_worker(Pool* p) {
  std::mt19937_64 rng(p->seed);
  std::vector<std::string> order = p->paths;
  if (p->shuffle) {
    for (size_t i = order.size(); i > 1; i--) {
      std::swap(order[i - 1], order[rng() % i]);
    }
  }
  // shuffle buffer of records (reservoir-style pool, the PyDataProvider2
  // pool_size shuffling semantics)
  std::vector<std::vector<uint8_t>> shuf_buf;
  const size_t kShufCap = p->shuffle ? 4096 : 0;

  auto emit = [&](std::vector<uint8_t>&& rec) -> bool {
    std::unique_lock<std::mutex> lk(p->mu);
    p->not_full.wait(lk, [&] {
      return p->queue.size() < p->queue_cap || p->stop.load();
    });
    if (p->stop.load()) return false;
    p->queue.emplace_back(std::move(rec));
    p->not_empty.notify_one();
    return true;
  };

  for (const auto& path : order) {
    if (p->stop.load()) break;
    FILE* f = fopen(path.c_str(), "rb");
    if (!f) continue;  // missing chunk: skip (master will requeue its task)
    char magic[4];
    if (!read_exact(f, magic, 4) || memcmp(magic, kMagic, 4) != 0) {
      fclose(f);
      continue;
    }
    while (!p->stop.load()) {
      uint32_t len, crc;
      if (!read_exact(f, &len, 4)) break;
      if (!read_exact(f, &crc, 4)) break;
      std::vector<uint8_t> rec(len);
      if (!read_exact(f, rec.data(), len)) break;
      if (crc32(rec.data(), len) != crc) break;  // torn tail: stop chunk
      if (kShufCap > 0) {
        if (shuf_buf.size() < kShufCap) {
          shuf_buf.emplace_back(std::move(rec));
        } else {
          size_t j = rng() % shuf_buf.size();
          std::swap(shuf_buf[j], rec);
          if (!emit(std::move(rec))) break;
        }
      } else {
        if (!emit(std::move(rec))) break;
      }
    }
    fclose(f);
  }
  if (kShufCap > 0 && !p->stop.load()) {
    for (size_t i = shuf_buf.size(); i > 1; i--)
      std::swap(shuf_buf[i - 1], shuf_buf[rng() % i]);
    for (auto& rec : shuf_buf)
      if (!emit(std::move(rec))) break;
  }
  p->done.store(true);
  p->not_empty.notify_all();
}

}  // namespace

extern "C" {

// ---------------------------------------------------------------- writer
void* ptr_writer_open(const char* path) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  if (fwrite(kMagic, 1, 4, f) != 4) {
    fclose(f);
    return nullptr;
  }
  return new Writer{f, ""};
}

int ptr_writer_append(void* w_, const uint8_t* data, uint32_t len) {
  Writer* w = static_cast<Writer*>(w_);
  uint32_t crc = crc32(data, len);
  if (fwrite(&len, 4, 1, w->f) != 1) return -1;
  if (fwrite(&crc, 4, 1, w->f) != 1) return -1;
  if (len > 0 && fwrite(data, 1, len, w->f) != len) return -1;
  return 0;
}

int ptr_writer_close(void* w_) {
  Writer* w = static_cast<Writer*>(w_);
  int rc = fclose(w->f);
  delete w;
  return rc;
}

// ---------------------------------------------------------------- reader
void* ptr_reader_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  char magic[4];
  if (!read_exact(f, magic, 4) || memcmp(magic, kMagic, 4) != 0) {
    fclose(f);
    return nullptr;
  }
  return new Reader{f, {}, ""};
}

// Returns pointer to an internal buffer valid until the next call;
// *len_out = record length, or -1 at EOF, -2 on CRC/torn-record error.
const uint8_t* ptr_reader_next(void* r_, int64_t* len_out) {
  Reader* r = static_cast<Reader*>(r_);
  uint32_t len, crc;
  if (!read_exact(r->f, &len, 4) || !read_exact(r->f, &crc, 4)) {
    *len_out = -1;
    return nullptr;
  }
  r->buf.resize(len);
  if (!read_exact(r->f, r->buf.data(), len) ||
      crc32(r->buf.data(), len) != crc) {
    *len_out = -2;
    return nullptr;
  }
  *len_out = static_cast<int64_t>(len);
  return r->buf.data();
}

void ptr_reader_close(void* r_) {
  Reader* r = static_cast<Reader*>(r_);
  fclose(r->f);
  delete r;
}

// ------------------------------------------- varint-framed proto shards
// The reference's ProtoDataProvider reads DataHeader/DataSample shards
// natively (paddle/gserver/dataproviders/ProtoDataProvider.cpp); this is
// the framing layer of that role: varint length prefix + message bytes,
// buffered stdio instead of Python's byte-at-a-time loop. Message
// PARSING stays in Python (protobuf gencode) — only IO is native.

void* ptr_vmsg_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  return new Reader{f, {}, ""};
}

// Next message into the internal buffer. *len_out: >=0 message length,
// -1 clean EOF (at a message boundary), -2 malformed/truncated shard.
const uint8_t* ptr_vmsg_next(void* r_, int64_t* len_out) {
  Reader* r = static_cast<Reader*>(r_);
  uint64_t value = 0;
  int shift = 0;
  int c = fgetc(r->f);
  if (c == EOF) {
    *len_out = -1;
    return nullptr;
  }
  while (true) {
    value |= static_cast<uint64_t>(c & 0x7F) << shift;
    if (!(c & 0x80)) break;
    shift += 7;
    if (shift > 63) {
      *len_out = -2;  // malformed varint
      return nullptr;
    }
    c = fgetc(r->f);
    if (c == EOF) {
      *len_out = -2;  // EOF inside varint
      return nullptr;
    }
  }
  r->buf.resize(value);
  if (value > 0 && !read_exact(r->f, r->buf.data(), value)) {
    *len_out = -2;  // truncated message body
    return nullptr;
  }
  *len_out = static_cast<int64_t>(value);
  return r->buf.data();
}

void ptr_vmsg_close(void* r_) {
  Reader* r = static_cast<Reader*>(r_);
  fclose(r->f);
  delete r;
}

// ------------------------------------------------------------------ pool
void* ptr_pool_create(const char** paths, int n_paths, int queue_cap,
                      int shuffle, uint64_t seed) {
  Pool* p = new Pool();
  for (int i = 0; i < n_paths; i++) p->paths.emplace_back(paths[i]);
  p->queue_cap = queue_cap > 0 ? queue_cap : 1024;
  p->shuffle = shuffle != 0;
  p->seed = seed;
  p->worker = std::thread(pool_worker, p);
  return p;
}

// Pops one record into caller-provided buffer. Returns record length
// (>=0), -1 when the pool is exhausted, -3 if the buffer is too small
// (record length returned via *need).
int64_t ptr_pool_next(void* p_, uint8_t* out, int64_t cap, int64_t* need) {
  Pool* p = static_cast<Pool*>(p_);
  std::unique_lock<std::mutex> lk(p->mu);
  p->not_empty.wait(lk, [&] {
    return !p->queue.empty() || p->done.load() || p->stop.load();
  });
  if (p->queue.empty()) return -1;
  std::vector<uint8_t>& rec = p->queue.front();
  int64_t len = static_cast<int64_t>(rec.size());
  if (len > cap) {
    *need = len;
    return -3;
  }
  if (len > 0) memcpy(out, rec.data(), len);
  p->queue.pop_front();
  p->not_full.notify_one();
  return len;
}

void ptr_pool_destroy(void* p_) { delete static_cast<Pool*>(p_); }

}  // extern "C"
