"""Native (C++) runtime components, loaded via ctypes.

The reference implements its data/runtime plumbing natively
(`paddle/gserver/dataproviders`, the RecordIO chunks the Go master
dispatches, `paddle/utils/Queue.h`); this package is the TPU build's
equivalent — see ``src/native.cc``. ``load_library()`` compiles the
shared object on first use with the host toolchain (g++) and caches it
next to the sources; ``available()`` reports whether the native path can
be used (every consumer has a pure-Python fallback).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "src", "native.cc")
_SO = os.path.join(_DIR, "libpaddle_tpu_native.so")

_lock = threading.Lock()
_lib = None
_failed = False


def _build() -> bool:
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-pthread",
           "-o", _SO + ".tmp", _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=300)
        os.replace(_SO + ".tmp", _SO)
        return True
    except (subprocess.SubprocessError, OSError) as e:
        import logging
        logging.getLogger("paddle_tpu").warning(
            "native build failed (%s); using pure-Python fallbacks", e)
        return False


def load_library():
    """The ctypes library, building it if necessary; None if unavailable."""
    global _lib, _failed
    with _lock:
        if _lib is not None or _failed:
            return _lib
        if not os.path.exists(_SO) or (os.path.getmtime(_SO)
                                       < os.path.getmtime(_SRC)):
            if not _build():
                _failed = True
                return None
        lib = ctypes.CDLL(_SO)
        lib.ptr_writer_open.restype = ctypes.c_void_p
        lib.ptr_writer_open.argtypes = [ctypes.c_char_p]
        lib.ptr_writer_append.restype = ctypes.c_int
        lib.ptr_writer_append.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint32]
        lib.ptr_writer_close.restype = ctypes.c_int
        lib.ptr_writer_close.argtypes = [ctypes.c_void_p]
        lib.ptr_reader_open.restype = ctypes.c_void_p
        lib.ptr_reader_open.argtypes = [ctypes.c_char_p]
        lib.ptr_reader_next.restype = ctypes.POINTER(ctypes.c_uint8)
        lib.ptr_reader_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64)]
        lib.ptr_reader_close.restype = None
        lib.ptr_reader_close.argtypes = [ctypes.c_void_p]
        lib.ptr_pool_create.restype = ctypes.c_void_p
        lib.ptr_pool_create.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_uint64]
        lib.ptr_pool_next.restype = ctypes.c_int64
        lib.ptr_pool_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int64)]
        lib.ptr_pool_destroy.restype = None
        lib.ptr_pool_destroy.argtypes = [ctypes.c_void_p]
        lib.ptr_vmsg_open.restype = ctypes.c_void_p
        lib.ptr_vmsg_open.argtypes = [ctypes.c_char_p]
        lib.ptr_vmsg_next.restype = ctypes.POINTER(ctypes.c_uint8)
        lib.ptr_vmsg_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64)]
        lib.ptr_vmsg_close.restype = None
        lib.ptr_vmsg_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return load_library() is not None
