"""Evaluators: metric computation over layer outputs.

Mirrors ``paddle/gserver/evaluators/Evaluator.{h,cpp}`` (classification
error, sum, column-sum; AUC/chunk/CTC land with the sequence phase). Each
evaluator is a pure function of the outputs dict, aggregated host-side
across batches the way ``Evaluator::start/eval/finish`` accumulates.
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp

from paddle_tpu.core.argument import Argument


def classification_error(output: Argument, label: Argument,
                         row_mask: jnp.ndarray = None) -> jnp.ndarray:
    """Fraction of rows whose argmax != label
    (``ClassificationErrorEvaluator``, Evaluator.cpp). Returns (errors,
    count) so the trainer can aggregate across batches. ``row_mask``
    ([B] f32, batch-bucket padding) removes dead rows from both the
    error sum and the count.

    This is the *device-side* stat producer used inside the jitted train/
    eval step; the host-side evaluator framework (including the richer
    top_k/weight variant of this metric) lives in
    ``paddle_tpu.trainer.metrics`` — same semantics when weight is None."""
    pred = jnp.argmax(output.value, axis=-1)
    lab = label.value.astype(pred.dtype)
    if (output.mask is not None and label.mask is not None
            and lab.ndim == pred.ndim and lab.shape[1] != pred.shape[1]):
        # differently-padded aligned sequences (sub-seq-aggregated output
        # vs feeder-padded labels): trim/pad labels to the output length
        T = pred.shape[1]
        lab = (lab[:, :T] if lab.shape[1] > T
               else jnp.pad(lab, ((0, 0), (0, T - lab.shape[1]))))
    wrong = (pred != lab).astype(jnp.float32)
    if output.mask is not None:
        # dead rows already carry an all-zero token mask; row_mask would
        # be redundant here
        wrong = wrong * output.mask
        count = jnp.sum(output.mask)
    elif row_mask is not None:
        wrong = wrong * row_mask
        count = jnp.sum(row_mask)
    else:
        count = jnp.float32(wrong.shape[0])
    return jnp.sum(wrong), count


class Accumulator:
    """Host-side metric accumulation (the CurrentEval/TotalEval split in
    ``TrainerInternal.cpp:160-170``)."""

    def __init__(self):
        self.totals: Dict[str, float] = {}
        self.counts: Dict[str, float] = {}

    def add(self, name: str, total, count):
        self.totals[name] = self.totals.get(name, 0.0) + float(total)
        self.counts[name] = self.counts.get(name, 0.0) + float(count)

    def result(self) -> Dict[str, float]:
        return {k: self.totals[k] / max(self.counts[k], 1.0)
                for k in self.totals}

    def reset(self):
        self.totals.clear()
        self.counts.clear()
