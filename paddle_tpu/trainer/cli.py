"""The ``paddle_tpu_trainer`` command (`paddle/trainer/TrainerMain.cpp`).

``python -m paddle_tpu.trainer.cli --config=model.py --job=train ...``

Job modes mirror the reference trainer:
- ``train``      — the training loop (+ checkpointing into --save_dir)
- ``test``       — one evaluation pass over the test reader
- ``time``       — steady-state ms/batch benchmark, skipping warmup
                   (`Trainer::time`, `TrainerBenchmark.cpp:27`)
- ``checkgrad``  — numeric-vs-analytic gradient check on one batch
                   (`Trainer::checkGradient`, `Trainer.cpp:299+`)
- ``merge``      — fuse config+params into one deploy file
                   (`MergeModel.cpp`)

The --config file is executed as Python (the reference's embedded-Python
`parse_config` contract, `TrainerConfigHelper.cpp:33-57`): it builds the
model with ``paddle_tpu.config.dsl`` or the v2 layer API and must define
``cost`` (a LayerOutput); optionally ``optimizer``, ``train_reader``,
``test_reader``, ``feeding`` (dict name->data_type), ``outputs``
(inference layers). ``--config_args a=1,b=x`` are injected as variables
before execution, exactly like the reference flag.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Optional


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="paddle_tpu_trainer",
        description="TPU trainer (paddle_trainer equivalent)")
    p.add_argument("--config", required=True,
                   help="Python model-config file (executed)")
    p.add_argument("--job", default="train",
                   choices=["train", "test", "time", "checkgrad", "merge",
                            "serve", "serve_fleet", "serve_train"])
    p.add_argument("--config_args", default="",
                   help="comma-separated k=v injected into the config")
    p.add_argument("--num_passes", type=int, default=1)
    p.add_argument("--log_period", type=int, default=100)
    p.add_argument("--dot_period", type=int, default=0,
                   help="print a progress dot every N batches")
    p.add_argument("--show_parameter_stats_period", type=int, default=0,
                   help="log the parameter health dump every N batches")
    p.add_argument("--show_layer_stat", action="store_true",
                   help="log per-layer output stats at each log_period "
                        "(read from the in-step telemetry when "
                        "--show_parameter_stats_period arms it)")
    p.add_argument("--log_error_clipping", action="store_true",
                   help="arm the divergence sentry and log each trip "
                        "(the reference's --log_error_clipping, "
                        "Flags.cpp:69, machine-mapped: loss/grad "
                        "finiteness plus --error_clipping_threshold "
                        "checked INSIDE the compiled step)")
    p.add_argument("--error_clipping_threshold", type=float, default=0.0,
                   help="divergence-sentry gradient threshold: trip "
                        "when max|grad| exceeds this (0 = finiteness "
                        "only; the reference's per-layer "
                        "error_clipping_threshold attr as a global "
                        "training-health knob). The policy on a trip "
                        "is --divergence_policy; skip_batch reproduces "
                        "the reference error-clipping semantics")
    p.add_argument("--divergence_policy", default="skip_batch",
                   choices=["halt", "skip_batch", "dump"],
                   help="what a sentry trip does: halt (postmortem + "
                        "DivergenceError), skip_batch (discard the "
                        "poisoned batch's update in-graph — the "
                        "post-skip trajectory is bitwise the run that "
                        "never saw the batch), dump (postmortem only, "
                        "keep training)")
    p.add_argument("--health_log", default=None,
                   help="append the per-step training-health timeline "
                        "(step, loss, lr, per-layer stats on period "
                        "steps, data_wait/compute) to this JSONL file "
                        "(obs/events.py; render/diff with "
                        "tools/healthview.py)")
    p.add_argument("--save_dir", default=None,
                   help="checkpoint directory (train) / source (test,merge)")
    p.add_argument("--saving_period", type=int, default=1)
    p.add_argument("--saving_period_by_batches", type=int, default=None)
    p.add_argument("--init_model_path", default=None,
                   help="checkpoint file or merged model to start from")
    p.add_argument("--auto_resume", default=True,
                   action=argparse.BooleanOptionalAction,
                   help="restore the newest intact checkpoint in "
                        "--save_dir before training (exact resume: RNG, "
                        "data position and schedule state included); "
                        "--no-auto_resume makes --save_dir save-only")
    p.add_argument("--background_save", default=True,
                   action=argparse.BooleanOptionalAction,
                   help="write checkpoints on a background thread — the "
                        "step loop never blocks on serialize/fsync "
                        "(device state is still snapshotted "
                        "synchronously, so the saved generation is "
                        "exact)")
    p.add_argument("--model_path", default=None,
                   help="output path for --job=merge")
    p.add_argument("--quantize", default=None, choices=["bf16", "int8"],
                   help="--job=merge: quantize weights into the PTM1 "
                        "artifact (per-tensor int8 scales / bf16 "
                        "storage cast, paddle_tpu/quant.py) and embed "
                        "the golden-request set the serving warmup "
                        "accuracy gate replays")
    p.add_argument("--quantize_tol", type=float, default=None,
                   help="override the per-dtype warmup-gate tolerance "
                        "recorded in the quantized artifact "
                        "(quant.GATE_TOLERANCES)")
    p.add_argument("--test_period", type=int, default=0,
                   help="run the test reader every N passes during train")
    p.add_argument("--trainer_count", type=int, default=1,
                   help=">1 builds a data-parallel mesh over that many "
                        "devices")
    p.add_argument("--use_gpu", default=None,
                   help="accepted for compatibility; device choice is "
                        "JAX's")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--prev_batch_state", action="store_true",
                   help="carry RNN state across batches (truncated BPTT, "
                        "the reference's --prev_batch_state)")
    p.add_argument("--fp_anomaly", action="store_true",
                   help="raise at the first op producing NaN/Inf (the "
                        "reference's feenableexcept, TrainerMain.cpp:49)")
    p.add_argument("--time_batches", type=int, default=20,
                   help="--job=time: timed batches after warmup")
    p.add_argument("--time_warmup", type=int, default=3)
    p.add_argument("--compute_dtype", default=None,
                   choices=["bfloat16", "float32"],
                   help="mixed precision (TPU-native addition): f32 "
                        "master params, forward/backward in this dtype")
    p.add_argument("--use_async_load_data", action="store_true",
                   help="decode/pad/shard/device_put batches in a "
                        "background thread, overlapped with the device "
                        "step (the reference's --use_async_load_data "
                        "double buffer, DataProvider.h:249)")
    p.add_argument("--prefetch_depth", type=int, default=2,
                   help="batches in flight under --use_async_load_data "
                        "(2 = double buffer)")
    p.add_argument("--metrics_port", type=int, default=0,
                   help="bind a /metrics exporter (Prometheus text + "
                        "?format=json) for this process: --job=train "
                        "exports the live StepBreakdown + per-device "
                        "memory_stats (the serving jobs already expose "
                        "/metrics on their HTTP frontend); 0 disables")
    p.add_argument("--show_step_breakdown", action="store_true",
                   help="log the per-step host-time split {data_wait, "
                        "h2d, compute, callback} and per-device "
                        "param/optimizer-slot bytes at each log_period")
    p.add_argument("--use_zero1", action="store_true",
                   help="ZeRO-1 sharded optimizer update: partition "
                        "optimizer state over the data axis (each device "
                        "holds 1/N of every slot), update shard-wise, "
                        "all-gather params — the pserver's sharded "
                        "update (ParameterServer2.cpp:362), TPU-native")
    p.add_argument("--fsdp", action="store_true",
                   help="full FSDP: shard PARAMETERS (not just optimizer "
                        "slots) flat-packed 1/N over a dedicated fsdp "
                        "mesh axis with one all-gather per layer on use "
                        "and reduce-scattered gradients "
                        "(optim/zero1.py:FsdpUpdater; "
                        "docs/spec_layout.md) — a model ~N× one "
                        "device's memory trains on N devices. The "
                        "--trainer_count width moves onto the fsdp axis "
                        "(batch rows still split over it, so the DP "
                        "degree is unchanged); composes with "
                        "--parallel_nn, --use_zero1 and seq-parallel "
                        "configs. Checkpoints stay format-compatible "
                        "crossing --fsdp on/off")
    p.add_argument("--fsdp_overlap", default="on",
                   choices=["on", "off", "force"],
                   help="--fsdp: overlap each layer's param all-gather "
                        "with the previous layer's compute (and the "
                        "grad reduce-scatters with backward) via an "
                        "optimization-barrier prefetch chain, double-"
                        "buffering at most two gathered layers "
                        "(optim/zero1.py:FsdpUpdater.full_params; "
                        "docs/spec_layout.md). 'on' engages on TPU "
                        "backends only (audit compiles on CPU keep the "
                        "sync spelling), 'force' engages everywhere, "
                        "'off' keeps the sync spelling")
    p.add_argument("--fused_rnn", action="store_true",
                   help="route LSTM/GRU cell math through the fused "
                        "kernel plane (paddle_tpu/kernels/): one Pallas "
                        "kernel per cell step on TPU, the bitwise-"
                        "identical jnp spelling elsewhere "
                        "(docs/kernels.md)")
    p.add_argument("--grad_accum_steps", type=int, default=1,
                   help="split each batch into k microbatches scanned "
                        "inside the jitted step, applying the optimizer "
                        "(and gradient clipping) once on the accumulated "
                        "gradient — a k× effective batch at 1/k the "
                        "activation memory")
    p.add_argument("--checkgrad_eps", type=float, default=1e-3,
                   help="--job=checkgrad finite-difference step (the "
                        "reference's --checkgrad_eps; default loosened "
                        "from 1e-5 because the engine computes in f32)")
    p.add_argument("--parallel_nn", action="store_true",
                   help="train the config's per-layer device placement "
                        "as a pipeline: layers pinned device=0..S-1 "
                        "become GPipe stages over an S-slot pipe mesh "
                        "axis, parameters sharded one stage per slot "
                        "(the reference's --parallel_nn, Flags.cpp:23 / "
                        "ParallelNeuralNetwork.h:23-62). Warns and "
                        "trains unpipelined when the config has no "
                        "device attrs or devices are short")
    p.add_argument("--pipeline_microbatches", type=int, default=0,
                   help="microbatches per batch under --parallel_nn "
                        "(bubble fraction = (S-1)/(S+M-1)); 0 = auto "
                        "(the stage count, or --grad_accum_steps)")
    # --job=serve (paddle_tpu.serving): the model server
    p.add_argument("--port", type=int, default=8000,
                   help="--job=serve: HTTP port (0 = ephemeral)")
    p.add_argument("--host", default="127.0.0.1",
                   help="--job=serve: bind address")
    p.add_argument("--batch_timeout_ms", type=float, default=5.0,
                   help="--job=serve: how long the dynamic batcher waits "
                        "to coalesce concurrent requests into one "
                        "device batch")
    p.add_argument("--max_batch", type=int, default=32,
                   help="--job=serve: largest coalesced batch (also the "
                        "largest warmed batch bucket; buckets double "
                        "1,2,4,... up to it)")
    p.add_argument("--queue_depth", type=int, default=128,
                   help="--job=serve: bounded request queue; past the "
                        "shed watermark new requests get a typed 429 "
                        "with Retry-After")
    p.add_argument("--shed_watermark", type=int, default=0,
                   help="--job=serve: queue depth that triggers load "
                        "shedding (0 = queue_depth)")
    p.add_argument("--serving_length_buckets", default="32,64,128",
                   help="--job=serve: comma-separated padded sequence "
                        "lengths to warm (the closed shape menu); "
                        "requests longer than the largest are rejected "
                        "with a typed 400")
    p.add_argument("--serving_deadline_ms", type=float, default=0,
                   help="--job=serve: default per-request deadline "
                        "(0 = none; requests may set their own)")
    p.add_argument("--decode_chunk", type=int, default=None,
                   help="decoder steps per compiled chunk of the "
                        "early-exit beam search (core/generation.py): "
                        "the search exits at the first chunk boundary "
                        "where every beam finished, so decode cost is "
                        "proportional to actual output length, not "
                        "max_length. 0 = full-length scan (the escape "
                        "hatch / A-B baseline); unset = the config's "
                        "pinned decode policy, else chunks of 8")
    p.add_argument("--serving_continuous_batching", action="store_true",
                   help="--job=serve: continuous batching for "
                        "/v1/generate — finished lanes retire and "
                        "queued requests are admitted at every "
                        "--decode_chunk boundary, so one slow request "
                        "no longer convoys its batch and deadlines are "
                        "enforced mid-decode")
    p.add_argument("--replicas", type=int, default=1,
                   help="--job=serve: run N replica engines behind the "
                        "health-aware router (serving/router.py): "
                        "failover on replica death, circuit breakers, "
                        "auto-respawn, rolling reload via POST "
                        "/admin/reload. Each replica warms from the "
                        "shared --aot_cache_dir, so replicas 2..N (and "
                        "every respawn) cold-start in milliseconds")
    p.add_argument("--aot_cache_dir", default=None,
                   help="--job=serve: persist the warmed bucket menu as "
                        "serialized compiled executables keyed by model "
                        "hash x bucket x jax/XLA version "
                        "(serving/aot_cache.py); a respawned replica "
                        "deserializes the menu instead of re-tracing "
                        "it. Misses/stale/corrupt entries fall back to "
                        "the live trace")
    p.add_argument("--hedge_ms", type=float, default=0,
                   help="--job=serve with --replicas>1: fire a capped "
                        "second attempt for an unanswered idempotent "
                        "score request after this many ms (never for "
                        "generate); 0 = hedging off")
    # --job=serve_fleet (serving/supervisor.py): the self-operating
    # fleet — supervisor-spawned single-replica server PROCESSES behind
    # the router, load-driven autoscaling, router HA via a warm standby
    p.add_argument("--min_replicas", type=int, default=1,
                   help="--job=serve_fleet: autoscale floor (the "
                        "supervisor spawns this many replica processes "
                        "at start)")
    p.add_argument("--max_replicas", type=int, default=None,
                   help="--job=serve_fleet: autoscale ceiling (default: "
                        "min_replicas — autoscaling pinned off)")
    p.add_argument("--standby", action="store_true",
                   help="--job=serve_fleet: run this router as the WARM "
                        "STANDBY — frontend bound and answering (503 "
                        "until adoption), watching --peer's /healthz; "
                        "on the active's death it takes the role lease "
                        "and adopts the replica set")
    p.add_argument("--peer", default=None,
                   help="--job=serve_fleet --standby: host:port of the "
                        "active router frontend to watch")
    p.add_argument("--fleet_lease", default=None,
                   help="--job=serve_fleet: path of the active-role "
                        "lease file BOTH routers share (FileStore; the "
                        "epoch-fenced election record). Required when a "
                        "--standby is deployed")
    p.add_argument("--lease_timeout_s", type=float, default=5.0,
                   help="--job=serve_fleet: replica liveness lease — a "
                        "replica whose health probes stop renewing for "
                        "this long is SIGTERM/SIGKILLed and respawned; "
                        "also the active-role lease ttl")
    p.add_argument("--autoscale_up_backlog_ms", type=float, default=50.0,
                   help="--job=serve_fleet: EWMA fleet backlog above "
                        "this (sustained) scales up")
    p.add_argument("--autoscale_down_backlog_ms", type=float,
                   default=5.0,
                   help="--job=serve_fleet: EWMA fleet backlog below "
                        "this (sustained) scales down")
    p.add_argument("--slo_p99_ms", type=float, default=0,
                   help="--job=serve: attach the online SLO controller "
                        "(serving/tuner.py:SLOController) targeting "
                        "this end-to-end p99; it nudges "
                        "batch_timeout_ms (and, when shedding at the "
                        "floor, max_batch within the warmed bucket "
                        "menu) through the same typed apply_config "
                        "path operators use, with Autoscaler-style "
                        "hysteresis. 0 (default) = off")
    p.add_argument("--slo_max_shed_rate", type=float, default=0.0,
                   help="--slo_p99_ms: shed-rate budget of the SLO "
                        "target — a windowed shed rate above this "
                        "counts as an SLO breach even when p99 is "
                        "inside target")
    p.add_argument("--workload_record", default=None,
                   help="--job=serve: tap the admission path "
                        "(serving/workload.py:WorkloadRecorder) and "
                        "write the offered stream — admitted AND shed "
                        "— to this WORKLOAD_*.json artifact at "
                        "shutdown, replayable via replay()/GridTuner "
                        "for offline tuning")
    # --job=serve_train (paddle_tpu/online): the online learning loop —
    # serving traffic streams into the trainer, publishes roll back out
    p.add_argument("--replay_dir", default=None,
                   help="--job=serve_train: replay-log directory — the "
                        "serving engines append answered score rows "
                        "here (durable PTRL1 segments), the tailer "
                        "trains them exactly-once through the ledger "
                        "(its snapshot lives here too), and the loop "
                        "resumes from it after a crash")
    p.add_argument("--publish_dir", default=None,
                   help="--job=serve_train: directory for published "
                        "PTM1 artifacts (model-vNNNN.ptmodel; default "
                        "<replay_dir>/published). --quantize applies "
                        "to every publish merge, gated by the serving "
                        "warmup accuracy gate — a refused artifact "
                        "rolls back and the incumbent keeps serving")
    p.add_argument("--publish_every", type=int, default=50,
                   help="--job=serve_train: publish + rolling hot-swap "
                        "cadence in trained batches")
    p.add_argument("--replay_segment_records", type=int, default=200,
                   help="--job=serve_train: rows per replay segment "
                        "before the fsync'd seal makes it visible to "
                        "the tailer (the durability granularity of the "
                        "serving->training edge)")
    p.add_argument("--replay_batch_rows", type=int, default=100,
                   help="--job=serve_train: rows per training batch "
                        "assembled from a sealed segment")
    p.add_argument("--serve_train_batches", type=int, default=0,
                   help="--job=serve_train: close the stream after this "
                        "many trained batches (0 = run until killed; "
                        "the durable replay+ledger+checkpoint state "
                        "resumes the loop exactly-once on restart)")
    args = p.parse_args(argv)
    if args.publish_dir is None and args.replay_dir:
        args.publish_dir = os.path.join(args.replay_dir, "published")
    return args


def load_config(path: str, config_args: str = ""):
    """Execute the config file; returns its namespace. Configs that import
    the v1 surface (``from paddle.trainer_config_helpers import *``) go
    through the compat config compiler (the reference's embedded
    ``parse_config`` contract, ``TrainerConfigHelper.cpp:33-57``) so
    reference configs run unmodified; native configs are executed directly
    and must define ``cost``."""
    import re
    with open(path) as f:
        src = f.read()
    # route on actual import statements, not mere mentions in comments;
    # .conf files are ALWAYS v1 configs — the oldest ones use the bare
    # @config_func spelling (default_initial_std, TrainData, Layer...)
    # with no import at all (paddle_trainer injected the names)
    if path.endswith(".conf") or re.search(
            r"^\s*(from|import)\s+paddle\.trainer", src, re.M):
        return _load_v1_config(path, config_args)
    from paddle_tpu.config import dsl
    dsl.reset()
    ns = {"__file__": os.path.abspath(path), "__name__": "__paddle_config__"}
    for kv in filter(None, config_args.split(",")):
        k, _, v = kv.partition("=")
        try:
            ns[k] = int(v)
        except ValueError:
            try:
                ns[k] = float(v)
            except ValueError:
                ns[k] = v
    with open(path) as f:
        code = compile(f.read(), path, "exec")
    exec(code, ns)
    if "cost" not in ns:
        raise SystemExit(f"config {path} must define `cost`")
    return ns


def _load_v1_config(path: str, config_args: str = ""):
    """v1 config -> the same namespace contract the native path produces
    (cost/optimizer/train_reader/test_reader/feeding/outputs)."""
    from paddle_tpu.compat import parse_config
    parsed = parse_config(path, config_args)

    out_names = list(parsed.context.output_layer_names)
    if not parsed.cost_layers() and not out_names:
        raise SystemExit(f"config {path} declares no outputs()")
    # --job=train on an inference-only topology fails later, by design
    cost = parsed.topology()

    ns = {
        "__file__": os.path.abspath(path),
        "parsed_config": parsed,
        "cost": cost,
        "optimizer": parsed.optimizer(),
        "feeding": parsed.feeding(),
        "outputs": out_names,
        "evaluators": list(parsed.context.evaluators),
    }
    ns["train_reader"] = (parsed.train_reader()
                          if parsed.context.train_source else None)
    ns["test_reader"] = (parsed.test_reader()
                         if parsed.context.test_source else None)
    return ns


def _build_trainer(ns, args):
    from paddle_tpu.optim.optimizers import Momentum
    from paddle_tpu.trainer.trainer import SGD, Topology
    topo = (ns["cost"] if isinstance(ns["cost"], Topology)
            else Topology(ns["cost"]))
    mesh = None
    n_pipe = 1
    if getattr(args, "parallel_nn", False):
        # the reference flag: per-layer device placement becomes GPipe
        # stages (ParallelNeuralNetwork.h:23-62); the mesh needs a pipe
        # axis exactly as wide as the config's stage count
        import jax

        from paddle_tpu.parallel.pipeline import split_pipeline_graph
        from paddle_tpu.utils import logger
        try:
            stages, _ = split_pipeline_graph(topo.graph)
            n_pipe = len(stages)
        except ValueError as e:
            logger.warning("--parallel_nn: %s — training unpipelined", e)
        n_data = max(args.trainer_count, 1)
        if n_pipe > 1 and len(jax.devices()) < n_pipe * n_data:
            logger.warning(
                "--parallel_nn: %d stages x trainer_count %d needs %d "
                "devices, have %d — training unpipelined",
                n_pipe, n_data, n_pipe * n_data, len(jax.devices()))
            n_pipe = 1
    n_fsdp = 1
    if getattr(args, "fsdp", False):
        # the data-parallel width moves onto the fsdp axis: batch rows
        # still split over it (mesh.batch_axes includes fsdp), but
        # parameters/slots pack 1/N per device instead of replicating
        import jax
        n_fsdp = (max(args.trainer_count, 1) if args.trainer_count > 1
                  else len(jax.devices()) // max(n_pipe, 1))
        if n_fsdp <= 1:
            from paddle_tpu.utils import logger
            logger.warning(
                "--fsdp: only %d device(s) available per pipeline "
                "stage — nothing to shard parameters over; training "
                "with the replicated layout", n_fsdp)
            n_fsdp = 1
    if n_pipe > 1 or n_fsdp > 1:
        from paddle_tpu.parallel import create_mesh
        mesh = create_mesh(
            n_data=(max(args.trainer_count, 1) if n_fsdp == 1 else 1),
            n_fsdp=n_fsdp, n_pipe=n_pipe)
    elif args.trainer_count > 1:
        from paddle_tpu.parallel import create_mesh
        mesh = create_mesh(n_data=args.trainer_count)
    optimizer = ns.get("optimizer") or Momentum(learning_rate=0.01,
                                                momentum=0.9)
    if getattr(args, "fused_rnn", False):
        from paddle_tpu import kernels
        kernels.set_fused_rnn(True)
    dtype = getattr(args, "compute_dtype", None)
    trainer = SGD(cost=topo, update_equation=optimizer, mesh=mesh,
                  seed=args.seed, evaluators=ns.get("evaluators"),
                  prev_batch_state=getattr(args, "prev_batch_state", False),
                  compute_dtype=None if dtype in (None, "float32") else dtype)
    if args.init_model_path:
        # BEFORE enable_pipeline: init files carry flat per-stage names
        # and _init_params maps them through the (flat) meta
        _init_params(trainer, args.init_model_path)
    if n_pipe > 1:
        # enabled HERE so every --job (train/time/...) sees the
        # pipelined step; SGD.train(pipeline=None) keeps the mode sticky
        trainer.enable_pipeline(
            microbatches=getattr(args, "pipeline_microbatches", 0) or None)
    if n_fsdp > 1:
        # likewise HERE (after the pipeline stacks its body, so the
        # fsdp plan sees the final layout); train(fsdp=None) is sticky
        overlap = {"on": True, "off": False, "force": "force"}[
            getattr(args, "fsdp_overlap", "on")]
        trainer.enable_fsdp(overlap=overlap)
    return trainer


def _init_params(trainer, path):
    import os
    if os.path.isdir(path):
        # a reference pass/model directory: one Parameter::save binary
        # file per parameter (the --init_model_path contract,
        # Trainer.cpp:229-250) — reference-trained models load directly
        import jax.numpy as jnp

        from paddle_tpu.compat.param_format import load_v1_model_dir
        raw = load_v1_model_dir(path)
        params = dict(trainer.params)
        missing, loaded = [], 0
        for name, spec in trainer.meta.items():
            if name not in raw:
                missing.append(name)
                continue
            flat = raw[name]
            want = 1
            for d in spec.shape:
                want *= int(d)
            if flat.size != want:
                raise ValueError(
                    f"--init_model_path: parameter {name!r} has "
                    f"{flat.size} values, the model needs {want} "
                    f"(shape {spec.shape}; fused-gate layouts may need "
                    "repacking)")
            params[name] = jnp.asarray(flat.reshape(spec.shape))
            loaded += 1
        if missing:
            from paddle_tpu.utils import logger
            logger.warning("--init_model_path: %d parameters missing in "
                           "%s (kept initialized): %s", len(missing),
                           path, missing[:5])
        trainer.load_state(params)
        return
    if path.endswith(".ptmodel"):
        from paddle_tpu.trainer.merge_model import load_merged
        _, params, _ = load_merged(path)
        trainer.load_state(params)
    else:
        from paddle_tpu.trainer.checkpoint import load_params
        params, opt_flat = load_params(path)
        trainer.load_state(params, opt_flat)


def _feeder(ns):
    from paddle_tpu.data.feeder import DataFeeder
    feeding = ns.get("feeding")
    return DataFeeder(feeding) if isinstance(feeding, dict) else feeding


def cmd_train(ns, args):
    from paddle_tpu.trainer import events as ev
    trainer = _build_trainer(ns, args)
    reader = ns.get("train_reader")
    if reader is None:
        raise SystemExit("config must define `train_reader` for --job=train")
    ck = None
    if args.save_dir:
        from paddle_tpu.dist.checkpoint import Checkpointer
        ck = Checkpointer(args.save_dir, saving_period=args.saving_period,
                          saving_period_by_batches=(
                              args.saving_period_by_batches),
                          background=getattr(args, "background_save", True))

    test_reader = ns.get("test_reader")
    feeder = _feeder(ns)

    def handler(e):
        if isinstance(e, ev.EndPass):
            print(f"Pass {e.pass_id}: " + " ".join(
                f"{k}={v:.5g}" for k, v in e.evaluator.items()))
            if (test_reader is not None and args.test_period
                    and (e.pass_id + 1) % args.test_period == 0):
                res = trainer.test(test_reader, feeder=feeder)
                print(f"  Test: cost={res.cost:.5g} " + " ".join(
                    f"{k}={v:.5g}" for k, v in res.evaluator.items()))

    # training-health plane: the sentry flags arm the in-step
    # finiteness/threshold check; --health_log adds the JSONL scalar
    # timeline; --show_parameter_stats_period arms the fused per-layer
    # telemetry inside trainer.train (the dedupe — no second forward)
    health = None
    sentry = bool(getattr(args, "error_clipping_threshold", 0.0)
                  or getattr(args, "log_error_clipping", False))
    if sentry or getattr(args, "health_log", None):
        health = {
            "sentry": sentry,
            "grad_threshold": getattr(args, "error_clipping_threshold",
                                      0.0),
            "policy": getattr(args, "divergence_policy", "skip_batch"),
            "log_clipping": getattr(args, "log_error_clipping", False),
            "log_path": getattr(args, "health_log", None),
        }

    metrics_srv = None
    if getattr(args, "metrics_port", 0):
        # metrics federation for the training side: the SAME scrape
        # surface the serving fleet has, exporting the live
        # StepBreakdown split + per-device memory accounting + the
        # training-health snapshot (pillar 4) — so the router-side
        # federation pattern shows trainer health with zero extra code
        from paddle_tpu.obs import MetricsRegistry, serve_metrics

        def train_snapshot():
            out = {"step_breakdown": trainer.breakdown.summary()}
            try:
                from paddle_tpu.utils.profiler import memory_stats
                out["memory"] = memory_stats(
                    trainer.params, getattr(trainer, "opt_state", None))
            except Exception as e:  # noqa: BLE001 — a scrape must
                # never interrupt training
                out["memory"] = {"error": repr(e)}
            return out

        def health_snapshot():
            hm = getattr(trainer, "_health", None)
            return hm.snapshot() if hm is not None else {"armed": False}

        registry = MetricsRegistry().register("train", train_snapshot)
        registry.register("health", health_snapshot)
        metrics_srv = serve_metrics(registry, host=args.host,
                                    port=args.metrics_port)
        print(f"train metrics on http://{args.host}:"
              f"{metrics_srv.server_address[1]}/metrics", flush=True)
    try:
        trainer.train(reader, feeder=feeder, num_passes=args.num_passes,
                      event_handler=handler, log_period=args.log_period,
                      dot_period=args.dot_period,
                      show_parameter_stats_period=(
                          args.show_parameter_stats_period),
                      show_layer_stat=args.show_layer_stat,
                      async_load_data=getattr(args, "use_async_load_data",
                                              False),
                      prefetch_depth=getattr(args, "prefetch_depth", 2),
                      show_step_breakdown=getattr(args,
                                                  "show_step_breakdown",
                                                  False),
                      zero1=True if getattr(args, "use_zero1", False)
                      else None,
                      fsdp=True if getattr(args, "fsdp", False) else None,
                      grad_accum_steps=getattr(args, "grad_accum_steps",
                                               1),
                      checkpointer=ck,
                      auto_resume=getattr(args, "auto_resume", True),
                      health=health)
    finally:
        if metrics_srv is not None:
            metrics_srv.shutdown()
            metrics_srv.server_close()
    return 0


def cmd_test(ns, args):
    trainer = _build_trainer(ns, args)
    if not args.init_model_path and args.save_dir:
        from paddle_tpu.dist.checkpoint import Checkpointer
        restored = Checkpointer(args.save_dir).restore()
        if restored:
            trainer.load_state(restored[0], restored[1])
    reader = ns.get("test_reader") or ns.get("train_reader")
    res = trainer.test(reader, feeder=_feeder(ns))
    print(f"Test: cost={res.cost:.5g} " + " ".join(
        f"{k}={v:.5g}" for k, v in res.evaluator.items()))
    return 0


def cmd_time(ns, args):
    """`paddle_trainer --job=time`: steady-state batch latency. Batches
    whose shapes differ from the first (e.g. a smaller final partial
    batch) are excluded — their jit recompile would otherwise put XLA
    compile time inside the timed window."""
    trainer = _build_trainer(ns, args)
    reader = ns.get("train_reader")
    if reader is None:
        raise SystemExit("config must define `train_reader` for --job=time")
    feeder = _feeder(ns)
    want = args.time_warmup + args.time_batches
    batches = []
    while len(batches) < want:
        before = len(batches)
        for data in reader():
            batches.append(data)
            if len(batches) >= want:
                break
        if len(batches) == before:
            break  # reader is empty/exhausted; time what we have
    if not batches:
        raise SystemExit("train_reader produced no batches")
    import jax
    import jax.numpy as jnp

    def shape_sig(feed):
        return tuple(sorted((k, v.value.shape) for k, v in feed.items()))

    times = []
    sig0 = None
    for i, data in enumerate(batches):
        feed = feeder(data) if feeder is not None else data
        sig = shape_sig(feed)
        sig0 = sig0 or sig
        trainer._rng, step_rng = jax.random.split(trainer._rng)
        t0 = time.perf_counter()
        trainer.params, trainer.opt_state, metrics = trainer._train_step(
            trainer.params, trainer.opt_state, feed, step_rng, jnp.int32(0))
        # a real host fetch, not block_until_ready: remote (tunneled)
        # devices report ready before execution finishes
        float(metrics["cost"])
        dt = time.perf_counter() - t0
        if i >= args.time_warmup and sig == sig0:
            times.append(dt)
    if not times:
        raise SystemExit("no steady-state batches to time (all warmup or "
                         "shape-mismatched)")
    ms = 1e3 * sum(times) / len(times)
    print(f"TimeInfo: avg_batch_time={ms:.3f}ms over {len(times)} batches "
          f"(skipped {args.time_warmup} warmup)")
    return 0


def cmd_checkgrad(ns, args, *, epsilon=None, rtol=5e-2, samples=6):
    """Numeric gradient check on one batch (`Trainer::checkGradient`).
    rtol is loose relative to the reference's double-precision check:
    the engine computes in float32, so the central difference itself
    carries ~1e-2 relative noise."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    if epsilon is None:
        epsilon = getattr(args, "checkgrad_eps", 1e-3)
    trainer = _build_trainer(ns, args)
    reader = ns.get("train_reader")
    feeder = _feeder(ns)
    data = next(iter(reader()))
    feed = feeder(data) if feeder is not None else data
    network, cost_name = trainer.network, trainer.topology.cost_name
    # the flat per-stage view: under --parallel_nn the live params are
    # stage-stacked, but the check runs the plain graph
    tparams = trainer._flat_params_view()

    # feed is a traced argument, not a closure capture: XLA embeds
    # captures as program constants (graftlint PT101, the ~4x/step
    # deopt class) — and the numeric loop below re-calls loss_fn with
    # perturbed params against the SAME embedded batch either way
    @jax.jit
    def loss_fn(params, feed):
        out = network.apply(params, feed, train=False)
        return jnp.sum(out[cost_name].value) / out[cost_name].value.shape[0]

    analytic = jax.jit(jax.grad(loss_fn))(tparams, feed)
    rng = np.random.RandomState(args.seed)
    worst = 0.0
    failed = []
    for name, g in analytic.items():
        if trainer.network.param_specs[name].is_static:
            continue
        p0 = np.asarray(tparams[name], dtype=np.float64)
        for idx in rng.choice(p0.size, size=min(samples, p0.size),
                              replace=False):
            delta = np.zeros(p0.size)
            delta[idx] = epsilon
            delta = delta.reshape(p0.shape)
            pp = dict(tparams)
            pp[name] = jnp.asarray(p0 + delta, jnp.float32)
            pm = dict(tparams)
            pm[name] = jnp.asarray(p0 - delta, jnp.float32)
            num = (float(loss_fn(pp, feed))
                   - float(loss_fn(pm, feed))) / (2 * epsilon)
            ana = float(np.asarray(g).reshape(-1)[idx])
            denom = max(abs(num), abs(ana), 1e-4)
            rel = abs(num - ana) / denom
            worst = max(worst, rel)
            if rel > rtol:
                failed.append((name, int(idx), num, ana))
    if failed:
        for name, idx, num, ana in failed[:10]:
            print(f"FAIL {name}[{idx}]: numeric={num:.6g} "
                  f"analytic={ana:.6g}")
        print(f"checkgrad FAILED ({len(failed)} mismatches, "
              f"worst rel err {worst:.3g})")
        return 1
    print(f"checkgrad PASSED (worst rel err {worst:.3g})")
    return 0


def cmd_merge(ns, args):
    from paddle_tpu.config import dsl
    from paddle_tpu.trainer.merge_model import merge_model
    trainer = _build_trainer(ns, args)
    if not args.init_model_path and args.save_dir:
        from paddle_tpu.dist.checkpoint import Checkpointer
        restored = Checkpointer(args.save_dir).restore()
        if restored:
            trainer.load_state(restored[0], restored[1])
    out_path = args.model_path or "model.ptmodel"
    outputs = ns.get("outputs")
    names = ([o.name if hasattr(o, "name") else o for o in outputs]
             if outputs else [ns["cost"].name])
    params = trainer._params_for_save()
    quant_meta = golden = None
    if args.quantize:
        from paddle_tpu import quant as quant_lib
        feeding = ns.get("feeding")
        if not isinstance(feeding, dict):
            feeding = getattr(feeding, "feeding", None)
        if not isinstance(feeding, dict):
            raise SystemExit(
                "--quantize needs the config to define `feeding` "
                "(data-layer name -> InputType) so the golden "
                "warmup-gate set can be recorded with the artifact")
        # golden refs come from the UNQUANTIZED params — the fp32
        # reference side of the warmup accuracy gate
        golden = quant_lib.golden_section(
            trainer.topology.graph, params, names, feeding)
        sparse = {name for name, spec in trainer.meta.items()
                  if getattr(spec, "sparse_grad", False)}
        params, quant_meta = quant_lib.quantize_params(
            params, args.quantize, sparse_names=sparse)
        if args.quantize_tol is not None:
            quant_meta["tol"] = float(args.quantize_tol)
    merge_model(out_path, trainer.topology.graph, params,
                outputs=names, quant=quant_meta, golden=golden)
    tag = f" ({args.quantize} quantized)" if args.quantize else ""
    print(f"merged model written to {out_path}{tag}")
    return 0


def _ensure_generation_params(graph, params):
    """The trainer only initializes parameters reachable from the cost;
    a ``beam_search_group``'s hoisted step-net params and generated-word
    embedding are not, so serving a generating config from a fresh init
    would KeyError inside the jitted search. Fill the gaps (tiny random
    init) with a warning — real deployments load them via
    ``--init_model_path`` / a checkpoint, where the training-time
    decoder shares the same hoisted names."""
    import numpy as np

    import jax.numpy as jnp

    from paddle_tpu.core.registry import get_layer_impl
    from paddle_tpu.utils.log import get_logger
    rng = np.random.RandomState(0)
    missing = []
    for name, ldef in graph.layers.items():
        if ldef.type != "beam_search_group":
            continue
        impl = get_layer_impl("beam_search_group")
        for _, spec in impl.params(ldef, []).items():
            if spec.absolute_name not in params:
                missing.append(spec.absolute_name)
                params[spec.absolute_name] = jnp.asarray(
                    rng.randn(*spec.shape).astype(np.float32) * 0.01)
        g = ldef.attrs["gen"]
        if g["embedding_name"] not in params:
            missing.append(g["embedding_name"])
            params[g["embedding_name"]] = jnp.asarray(rng.randn(
                g["size"], g["embedding_size"]).astype(np.float32) * 0.01)
    if missing:
        get_logger("serving").warning(
            "generation parameters %s were not in the loaded/initialized "
            "table (the trainer only walks the cost graph); serving with "
            "fresh small-random values — load a trained model via "
            "--init_model_path for real generation", missing)


def _serving_plan(ns, args):
    """The shared --job=serve wiring: (graph, params, output names,
    feeding, predictor kwargs, engine kwargs) — everything a replica
    engine is built from. Parameter source order mirrors --job=test:
    --init_model_path (checkpoint file, merged .ptmodel, or a reference
    model dir), else the newest checkpoint in --save_dir; the config
    supplies graph + feeding + outputs."""
    trainer = _build_trainer(ns, args)
    if not args.init_model_path and args.save_dir:
        from paddle_tpu.dist.checkpoint import Checkpointer
        restored = Checkpointer(args.save_dir).restore()
        if restored:
            trainer.load_state(restored[0], restored[1])
    feeding = ns.get("feeding")
    if not isinstance(feeding, dict):
        feeding = getattr(feeding, "feeding", None)
    if not isinstance(feeding, dict):
        raise SystemExit("--job=serve needs the config to define "
                         "`feeding` (data-layer name -> InputType)")
    outputs = ns.get("outputs")
    names = ([o.name if hasattr(o, "name") else o for o in outputs]
             if outputs else [ns["cost"].name])
    max_batch = max(args.max_batch, 1)
    batch_buckets = [1]
    while batch_buckets[-1] < max_batch:
        batch_buckets.append(min(batch_buckets[-1] * 2, max_batch))
    length_buckets = [int(x) for x in filter(
        None, str(args.serving_length_buckets).split(","))]
    # None = inherit the config's pinned decode policy; 0 = full scan
    decode_chunk = getattr(args, "decode_chunk", None)
    params = dict(trainer._flat_params_view())
    pred_kwargs = dict(
        batch_buckets=batch_buckets, length_buckets=length_buckets,
        gen_decode_chunk=decode_chunk,
        gen_full_scan=(None if decode_chunk is None
                       else decode_chunk <= 0),
        aot_cache=getattr(args, "aot_cache_dir", None))
    mp = args.init_model_path
    if mp and mp.endswith(".ptmodel"):
        # A merged artifact owns its serving identity: the PTM1 digest
        # keys the AOT cache and names the published model_version (the
        # same identity the fleet reload path reports), and a
        # ``--quantize`` artifact's optional sections MUST reach the
        # predictor — the trainer round-trip above goes through the
        # extras-ignoring old reader, which would silently serve raw
        # storage-dtype leaves with no scales and no warmup gate.
        from paddle_tpu.trainer.merge_model import (load_merged_ex,
                                                    merged_digest)
        _, mparams, _, extras = load_merged_ex(mp)
        pred_kwargs["model_hash"] = merged_digest(mp)
        if extras.get("quant") or extras.get("golden"):
            params = dict(mparams)  # storage-dtype leaves, scales apart
            pred_kwargs["quant"] = extras.get("quant")
            pred_kwargs["golden"] = extras.get("golden")
    _ensure_generation_params(trainer.topology.graph, params)
    eng_kwargs = dict(
        max_batch=max_batch,
        batch_timeout_ms=args.batch_timeout_ms,
        queue_depth=args.queue_depth,
        shed_watermark=args.shed_watermark or None,
        default_deadline_ms=args.serving_deadline_ms or None,
        continuous_batching=getattr(args, "serving_continuous_batching",
                                    False))
    return trainer.topology.graph, params, names, feeding, \
        pred_kwargs, eng_kwargs


def build_serving_engine(ns, args):
    """One replica engine from the serving plan (tests and embedders
    build the engine without entering serve_forever)."""
    from paddle_tpu.serving import ServingEngine, ServingPredictor
    graph, params, names, feeding, pk, ek = _serving_plan(ns, args)
    return ServingEngine(
        ServingPredictor(graph, params, names, feeding, **pk), **ek)


def build_serving_fleet(ns, args):
    """--replicas N: N replica engines (each its own predictor, all
    warming from the shared --aot_cache_dir — replica 0 traces live and
    populates the cache, replicas 1..N-1 and every respawn deserialize
    it) behind the health-aware router. Returns ``(router,
    reload_builder)`` — the builder backs ``POST /admin/reload``
    (rolling hot-swap to a new merged artifact)."""
    from paddle_tpu.serving import (EngineTransport, ReplicaRouter,
                                    ServingEngine, ServingPredictor)
    graph, params, names, feeding, pk, ek = _serving_plan(ns, args)

    def make_engine(from_model_path=None):
        if from_model_path is not None:
            pred = ServingPredictor.from_merged(
                from_model_path, feeding, **pk)
        else:
            pred = ServingPredictor(graph, params, names, feeding, **pk)
        return ServingEngine(pred, **ek).start(warmup=True)

    transports = [EngineTransport(make_engine())
                  for _ in range(max(1, args.replicas))]
    # the respawn factory rebuilds a replica after worker death; the
    # reload builder swaps in a NEW artifact (both warm from the cache)
    router = ReplicaRouter(
        transports,
        spawn=lambda rid: EngineTransport(make_engine()),
        hedge_ms=(args.hedge_ms or None))

    def reload_builder(model_path, rid):
        return EngineTransport(make_engine(from_model_path=model_path))

    return router, reload_builder


def _replica_cmd(args, port):
    """The child command line for one supervised single-replica server:
    the parent's serving config re-spelled as ``--job=serve`` on its own
    port, with ``--aot_cache_dir`` threaded through so every respawn
    deserializes its bucket menu instead of re-tracing it."""
    cmd = [sys.executable, "-m", "paddle_tpu.trainer.cli",
           "--config", args.config, "--job", "serve",
           "--host", args.host, "--port", str(port),
           "--batch_timeout_ms", str(args.batch_timeout_ms),
           "--max_batch", str(args.max_batch),
           "--queue_depth", str(args.queue_depth),
           "--serving_length_buckets", str(args.serving_length_buckets)]
    if args.config_args:
        cmd += ["--config_args", args.config_args]
    if args.shed_watermark:
        cmd += ["--shed_watermark", str(args.shed_watermark)]
    if args.serving_deadline_ms:
        cmd += ["--serving_deadline_ms", str(args.serving_deadline_ms)]
    if args.decode_chunk is not None:
        cmd += ["--decode_chunk", str(args.decode_chunk)]
    if args.serving_continuous_batching:
        cmd += ["--serving_continuous_batching"]
    if args.aot_cache_dir:
        cmd += ["--aot_cache_dir", args.aot_cache_dir]
    if args.init_model_path:
        cmd += ["--init_model_path", args.init_model_path]
    elif args.save_dir:
        cmd += ["--save_dir", args.save_dir]
    return cmd


def cmd_serve_fleet(ns, args):
    """``--job=serve_fleet``: the self-operating fleet. The supervisor
    spawns ``--min_replicas`` real single-replica server processes
    (``--job=serve`` children) and leases their liveness; the router
    fronts them over HTTPTransports; the autoscaler moves the count
    inside ``[--min_replicas, --max_replicas]`` on the EWMA backlog
    signal. With ``--fleet_lease`` the router is role-fenced;
    ``--standby`` runs the warm-standby side instead (bound frontend,
    watching ``--peer``, adopting the fleet on the active's death)."""
    import subprocess

    from paddle_tpu.dist.master import FileStore, RoleLease
    from paddle_tpu.serving import (Autoscaler, ReplicaRouter,
                                    ReplicaSupervisor, RouterHA,
                                    serve_router_forever)
    from paddle_tpu.serving.supervisor import free_port

    min_r = max(1, args.min_replicas)
    max_r = args.max_replicas if args.max_replicas else min_r
    lease = None
    if args.fleet_lease:
        holder = f"{'standby' if args.standby else 'active'}-{os.getpid()}"
        lease = RoleLease(FileStore(args.fleet_lease), holder,
                          ttl_s=args.lease_timeout_s)
    elif args.standby:
        raise SystemExit("--standby needs --fleet_lease (the shared "
                         "role-election record both routers read)")

    if args.standby:
        if not args.peer:
            raise SystemExit("--standby needs --peer host:port (the "
                             "active router frontend to watch)")
        host, _, port = str(args.peer).rpartition(":")
        router = ReplicaRouter([], fence=lease)
        ha = RouterHA(router, lease,
                      peer=(host or "127.0.0.1", int(port)),
                      interval_ms=max(100.0,
                                      args.lease_timeout_s * 1e3 / 4))
        ha.start()
        try:
            return serve_router_forever(router, host=args.host,
                                        port=args.port)
        finally:
            ha.shutdown()

    def spawn(replica_id):
        port = free_port(args.host)
        proc = subprocess.Popen(_replica_cmd(args, port))
        return proc, args.host, port

    supervisor = ReplicaSupervisor(
        spawn, replicas=min_r, lease_timeout_s=args.lease_timeout_s,
        poll_ms=max(100.0, args.lease_timeout_s * 1e3 / 4))
    transports = supervisor.start(wait_ready_s=600.0)
    router = ReplicaRouter(transports, spawn=None, fence=lease,
                           hedge_ms=(args.hedge_ms or None),
                           metrics=supervisor.metrics)
    supervisor.attach_router(router)
    supervisor.start_monitor()
    ha = None
    if lease is not None:
        ha = RouterHA(router, lease,
                      interval_ms=max(100.0,
                                      args.lease_timeout_s * 1e3 / 4))
        ha.start(take_role=True)
    scaler = None
    if max_r > min_r:
        scaler = Autoscaler(
            supervisor, min_replicas=min_r, max_replicas=max_r,
            up_backlog_ms=args.autoscale_up_backlog_ms,
            down_backlog_ms=args.autoscale_down_backlog_ms).start()
    # metrics federation: the router frontend's /metrics additionally
    # carries the supervisor's replica table (+ the autoscale
    # trajectory) so ONE scrape shows the whole self-operating fleet
    from paddle_tpu.obs import MetricsRegistry
    registry = MetricsRegistry().register("supervisor",
                                          supervisor.snapshot)
    if scaler is not None:
        registry.register(
            "autoscaler",
            lambda: {"replicas": supervisor.replica_count(),
                     "ewma_backlog_ms": scaler.ewma,
                     "trajectory": [list(p) for p in
                                    scaler.trajectory[-64:]]})
    try:
        return serve_router_forever(router, host=args.host,
                                    port=args.port, registry=registry)
    finally:
        if scaler is not None:
            scaler.stop()
        if ha is not None:
            ha.shutdown()
        supervisor.shutdown(drain=True)


def build_serve_train_loop(ns, args, *, start_fleet=True):
    """The --job=serve_train wiring, reusable by bench/tests: returns
    ``(loop, router, writer)`` — a ready :class:`ServeTrainLoop`, the
    serving fleet fronting the published artifact (None when
    ``start_fleet=False``: the trainer-only mode), and the replay
    writer the engines append through.

    The loop closes over ONE trainer; the fleet never serves live
    trainer params — replicas are always built from a published PTM1
    artifact (v0 is merged before the first replica warms), so the
    running model is exactly the artifact its ``model_hash`` pins and a
    reload is a weight-only swap against an unchanged AOT menu."""
    from paddle_tpu.online import (ModelPublisher, ReplayTailer,
                                   ReplayWriter, ServeTrainLoop)
    if not args.replay_dir:
        raise SystemExit("--job=serve_train needs --replay_dir")
    graph, _params, names, feeding, pk, ek = _serving_plan(ns, args)
    del graph
    trainer = _build_trainer(ns, args)
    if not args.init_model_path and args.save_dir:
        from paddle_tpu.dist.checkpoint import Checkpointer
        restored = Checkpointer(args.save_dir).restore()
        if restored:
            trainer.load_state(restored[0], restored[1])
    publish_dir = args.publish_dir or os.path.join(args.replay_dir,
                                                   "published")
    writer = ReplayWriter(args.replay_dir,
                          segment_records=args.replay_segment_records,
                          schema=list(feeding))
    ek = dict(ek, replay_sink=writer)

    def make_engine(model_path):
        from paddle_tpu.serving import ServingEngine, ServingPredictor
        pred = ServingPredictor.from_merged(model_path, feeding, **pk)
        return ServingEngine(pred, **ek).start(warmup=True)

    def build_transport(model_path, rid):
        from paddle_tpu.serving import EngineTransport
        return EngineTransport(make_engine(model_path))

    publisher = ModelPublisher(
        trainer, model_dir=publish_dir, outputs=names,
        build_transport=build_transport,
        every_batches=args.publish_every,
        quantize=getattr(args, "quantize", None), feeding=feeding)
    router = None
    if start_fleet:
        from paddle_tpu.serving import EngineTransport, ReplicaRouter
        publisher.publish()  # v0: the fleet's starting artifact
        transports = [EngineTransport(make_engine(publisher.last_good))
                      for _ in range(max(1, args.replicas))]
        router = ReplicaRouter(
            transports,
            spawn=lambda rid: EngineTransport(
                make_engine(publisher.last_good)),
            hedge_ms=(args.hedge_ms or None))
        publisher.router = router

    ck = None
    if args.save_dir:
        from paddle_tpu.dist.checkpoint import Checkpointer
        ck = Checkpointer(
            args.save_dir, saving_period=args.saving_period,
            saving_period_by_batches=(args.saving_period_by_batches
                                      or 20),
            background=getattr(args, "background_save", True))
    tailer = ReplayTailer(args.replay_dir,
                          batch_rows=args.replay_batch_rows)
    # the divergence sentry is armed BY DEFAULT in-loop: an unattended
    # trainer fed by live traffic must not publish a poisoned update
    # (skip_batch discards it in-graph; flags tighten/loosen as in
    # --job=train)
    health = {
        "sentry": True,
        "grad_threshold": getattr(args, "error_clipping_threshold", 0.0),
        "policy": getattr(args, "divergence_policy", "skip_batch"),
        "log_clipping": getattr(args, "log_error_clipping", False),
        "log_path": getattr(args, "health_log", None),
    }
    loop = ServeTrainLoop(
        trainer, tailer=tailer, publisher=publisher, feeder=_feeder(ns),
        writer=writer, checkpointer=ck, health=health,
        max_batches=(args.serve_train_batches or None),
        log_period=args.log_period)
    return loop, router, writer


def cmd_serve_train(ns, args):
    """``--job=serve_train``: one supervised process group closing
    serving→training→publish→serving. The fleet serves (and its HTTP
    frontend binds) while the main thread trains the replay stream; on
    the batch budget (or SIGTERM) the stream closes, the reader drains,
    and the trainer unwinds through its end-of-pass commit."""
    import threading

    from paddle_tpu.serving.router import (
        install_router_signal_handlers, make_router_server)
    loop, router, writer = build_serve_train_loop(ns, args)
    router.start()
    server = make_router_server(router, args.host, args.port)
    install_router_signal_handlers(router, server)
    print(f"serve_train: router on http://{args.host}:"
          f"{server.server_address[1]}, publishing every "
          f"{args.publish_every} batches", flush=True)
    frontend = threading.Thread(target=server.serve_forever,
                                kwargs={"poll_interval": 0.2},
                                name="serve-train-frontend", daemon=True)
    frontend.start()
    try:
        loop.run()
    finally:
        loop.stop()
        server.shutdown()
        server.server_close()
        router.shutdown(drain=True)
        writer.close()
    print(f"serve_train: {loop.batches_trained} batches trained, "
          f"{loop.publisher.publishes_total} publishes "
          f"({loop.publisher.rollbacks_total} rollbacks)", flush=True)
    return 0


def cmd_serve(ns, args):
    if getattr(args, "replicas", 1) > 1:
        from paddle_tpu.serving import serve_router_forever
        router, reload_builder = build_serving_fleet(ns, args)
        return serve_router_forever(
            router, host=args.host, port=args.port,
            reload_builder=reload_builder,
            model_path=getattr(args, "model_path", None))
    from paddle_tpu.serving import serve_forever
    engine = build_serving_engine(ns, args)
    recorder = controller = None
    if getattr(args, "workload_record", None):
        from paddle_tpu.serving.workload import WorkloadRecorder
        recorder = WorkloadRecorder()
        engine.workload_recorder = recorder
    if getattr(args, "slo_p99_ms", 0):
        from paddle_tpu.serving.tuner import (SLOController, SLOTarget,
                                              engine_signal)
        controller = SLOController(
            engine,
            SLOTarget(p99_ms=args.slo_p99_ms,
                      max_shed_rate=args.slo_max_shed_rate),
            signal=engine_signal(engine),
            timeout_ms=args.batch_timeout_ms,
            timeout_lo_ms=min(0.5, args.batch_timeout_ms),
            timeout_hi_ms=max(50.0, args.batch_timeout_ms),
            max_batch=args.max_batch).start()
    try:
        return serve_forever(engine, host=args.host, port=args.port)
    finally:
        if controller is not None:
            controller.stop()
        if recorder is not None:
            engine.workload_recorder = None
            recorder.snapshot(
                os.path.splitext(os.path.basename(
                    args.workload_record))[0]).save(args.workload_record)


def main(argv=None):
    args = parse_args(argv)
    # deterministic fault injection (tools/chaos_soak.py arms children
    # through the env); a no-op unless PADDLE_TPU_CHAOS_PLAN is set
    from paddle_tpu.testing import chaos as _chaos
    _chaos.install_from_env()
    # observability plane (a no-op unless $PADDLE_TPU_TRACE_DIR /
    # $PADDLE_TPU_FLIGHT_DIR are set): spans + flight events dump at
    # exit, tagged with this process's job kind so tools/blackbox.py
    # can merge a whole fleet's dumps into one named timeline
    from paddle_tpu import obs
    obs.arm_from_env(args.job)
    if getattr(args, "fp_anomaly", False):
        from paddle_tpu.utils.fp import enable_fp_anomaly
        enable_fp_anomaly()
    ns = load_config(args.config, args.config_args)
    return {"train": cmd_train, "test": cmd_test, "time": cmd_time,
            "checkgrad": cmd_checkgrad, "merge": cmd_merge,
            "serve": cmd_serve, "serve_fleet": cmd_serve_fleet,
            "serve_train": cmd_serve_train}[args.job](ns, args)


if __name__ == "__main__":
    sys.exit(main())
