"""Checkpoint save/load.

Covers ``ParamUtil::saveParametersOnePass`` / ``Parameter::save/load``
(``paddle/trainer/ParamUtil.cpp``, ``paddle/parameter/Parameter.cpp``) and
v2's ``Parameters.to_tar/from_tar``: parameters (+ optional optimizer slot
state) to one .npz with an MD5 integrity sidecar — the integrity-checked
checkpoint style of the Go pserver (``go/pserver/service.go:75-84``).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, Optional

import jax
import numpy as np


def _flatten(tree: Any, prefix: str = "") -> Dict[str, np.ndarray]:
    flat = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            flat.update(_flatten(v, f"{prefix}{k}/"))
    else:
        flat[prefix.rstrip("/")] = np.asarray(jax.device_get(tree))
    return flat


def save_params(path: str, params: Dict[str, Any],
                opt_state: Optional[Any] = None, meta: Optional[dict] = None):
    """``params`` and ``opt_state`` may be zero-arg callables producing
    their trees (lazy export). The trainer's ZeRO-1 mode passes
    ``SGD._opt_state_for_save`` here so sharded optimizer slots are
    gathered back to their parameters' full shapes at save time, and the
    pipeline mode passes ``SGD._params_for_save`` so stage-stacked body
    parameters unstack to their flat per-stage names — the on-disk format
    (keys and shapes) never depends on the update path;
    ``SGD.load_state`` reshards/restacks on restore."""
    if callable(params):
        params = params()
    if callable(opt_state):
        opt_state = opt_state()
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    arrays = {f"param::{k}": np.asarray(jax.device_get(v))
              for k, v in params.items()}
    if opt_state is not None:
        arrays.update({f"opt::{k}": v
                       for k, v in _flatten(opt_state).items()})
    real_path = path if path.endswith(".npz") else path + ".npz"
    # atomic: a crash mid-save must never leave a torn file at the final
    # name (the recovery scan would have to skip it, and a torn .npz with
    # no .meta bypasses the MD5 gate)
    tmp = real_path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, real_path)
    md5 = hashlib.md5(open(real_path, "rb").read()).hexdigest()
    with open(real_path + ".meta.tmp", "w") as f:
        json.dump({"md5": md5, **(meta or {})}, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(real_path + ".meta.tmp", real_path + ".meta")


def load_params(path: str, check_integrity: bool = True):
    real_path = path if path.endswith(".npz") else path + ".npz"
    if check_integrity and os.path.exists(real_path + ".meta"):
        with open(real_path + ".meta") as f:
            meta = json.load(f)
        md5 = hashlib.md5(open(real_path, "rb").read()).hexdigest()
        if md5 != meta.get("md5"):
            raise IOError(f"checkpoint {real_path} failed MD5 integrity check"
                          " (WrongChecksum, go/pserver/service.go:49)")
    data = np.load(real_path)
    params = {}
    opt_flat = {}
    for k in data.files:
        if k.startswith("param::"):
            params[k[len("param::"):]] = data[k]
        elif k.startswith("opt::"):
            opt_flat[k[len("opt::"):]] = data[k]
    return params, opt_flat
