"""Checkpoint save/load.

Covers ``ParamUtil::saveParametersOnePass`` / ``Parameter::save/load``
(``paddle/trainer/ParamUtil.cpp``, ``paddle/parameter/Parameter.cpp``) and
v2's ``Parameters.to_tar/from_tar``: parameters (+ optional optimizer slot
state) to one .npz with an MD5 integrity sidecar — the integrity-checked
checkpoint style of the Go pserver (``go/pserver/service.go:75-84``).

Exact-resume extension: a checkpoint may additionally carry *trainer
state* — everything outside params/opt_state that the training
trajectory depends on (the step RNG key, truncated-BPTT carried state,
…) — under a third ``state::`` namespace in the same .npz, so a resumed
run is bitwise the uninterrupted one (docs/fault_tolerance.md lists the
full state inventory). Array-valued entries store directly; arbitrary
pytrees (the carried-state dict) store as a pickled uint8 buffer under
``stateobj::`` — self-contained, no ``allow_pickle`` at load time for
the array entries, and the ``stateobj::`` pickles deserialize through a
restricted unpickler that admits only numpy array machinery and stdlib
containers (the MD5 sidecar is integrity, not authenticity — a crafted
checkpoint in a shared save dir must not execute code at restore()).
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import pickle
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np


def _flatten(tree: Any, prefix: str = "") -> Dict[str, np.ndarray]:
    flat = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            flat.update(_flatten(v, f"{prefix}{k}/"))
    else:
        flat[prefix.rstrip("/")] = np.asarray(jax.device_get(tree))
    return flat


def save_params(path: str, params: Dict[str, Any],
                opt_state: Optional[Any] = None, meta: Optional[dict] = None,
                extra_state: Optional[Dict[str, Any]] = None):
    """``params`` and ``opt_state`` may be zero-arg callables producing
    their trees (lazy export). The trainer's ZeRO-1 mode passes
    ``SGD._opt_state_for_save`` here so sharded optimizer slots are
    gathered back to their parameters' full shapes at save time, and the
    pipeline mode passes ``SGD._params_for_save`` so stage-stacked body
    parameters unstack to their flat per-stage names — the on-disk format
    (keys and shapes) never depends on the update path;
    ``SGD.load_state`` reshards/restacks on restore.

    ``extra_state`` entries: arrays land under ``state::<key>``; any
    other non-None value (a pytree) is pickled under ``stateobj::<key>``
    after ``device_get`` (so only host numpy crosses the pickle)."""
    arrays = snapshot_arrays(params, opt_state, extra_state)
    write_snapshot(path, arrays, meta)


def snapshot_arrays(params, opt_state=None, extra_state=None
                    ) -> Dict[str, np.ndarray]:
    """Resolve lazy callables and fetch everything to host numpy — the
    synchronous half of a save. What remains (``write_snapshot``) is
    pure file I/O that a background thread can own, after the step loop
    has moved on and possibly donated the device buffers away."""
    if callable(params):
        params = params()
    if callable(opt_state):
        opt_state = opt_state()
    if callable(extra_state):
        extra_state = extra_state()
    arrays = {f"param::{k}": np.asarray(jax.device_get(v))
              for k, v in params.items()}
    if opt_state is not None:
        arrays.update({f"opt::{k}": v
                       for k, v in _flatten(opt_state).items()})
    for k, v in (extra_state or {}).items():
        if v is None:
            continue
        if hasattr(v, "shape") and hasattr(v, "dtype"):
            arrays[f"state::{k}"] = np.asarray(jax.device_get(v))
        else:
            host = jax.tree_util.tree_map(
                lambda x: np.asarray(jax.device_get(x))
                if hasattr(x, "dtype") else x, v)
            arrays[f"stateobj::{k}"] = np.frombuffer(
                pickle.dumps(host), dtype=np.uint8)
    return arrays


class _StateUnpickler(pickle.Unpickler):
    """``stateobj::`` entries are pytrees of HOST numpy arrays (the
    carried BPTT dict after ``device_get``): the only globals their
    pickles legitimately reference are numpy's array reconstructors and
    stdlib containers. Anything else is a tampered checkpoint — the MD5
    sidecar is integrity, not authenticity, and a plain pickle.loads
    would execute whatever a crafted file references at restore()."""

    _ALLOWED = {
        ("numpy.core.multiarray", "_reconstruct"),
        ("numpy._core.multiarray", "_reconstruct"),
        ("numpy.core.multiarray", "scalar"),
        ("numpy._core.multiarray", "scalar"),
        ("numpy", "ndarray"),
        ("numpy", "dtype"),
        ("collections", "OrderedDict"),
    }

    def find_class(self, module, name):
        # ml_dtypes: jax's extension dtypes (bfloat16 etc.) — a bf16
        # carried state pickles a reference to its dtype class, and
        # rejecting it would make every mixed-precision checkpoint
        # "corrupt" (restore() would silently fall through all
        # generations to a fresh start)
        if (module, name) in self._ALLOWED or \
                module in ("numpy.dtypes", "ml_dtypes"):
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"checkpoint trainer-state references {module}.{name}; only "
            "numpy arrays and plain containers restore (tampered or "
            "incompatible stateobj:: entry)")


def _loads_state(raw: bytes):
    return _StateUnpickler(io.BytesIO(raw)).load()


def write_snapshot(path: str, arrays: Dict[str, np.ndarray],
                   meta: Optional[dict] = None) -> str:
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    real_path = path if path.endswith(".npz") else path + ".npz"
    # atomic: a crash mid-save must never leave a torn file at the final
    # name (the recovery scan would have to skip it, and a torn .npz with
    # no .meta bypasses the MD5 gate)
    tmp = real_path + ".tmp"
    # serialize ONCE to memory and hash those bytes: the digest covers
    # exactly what lands on disk, without re-reading a model-sized file
    # per generation (the load side makes the same single-read pledge)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    data = buf.getbuffer()  # zero-copy view: ONE serialized copy in RAM
    md5 = hashlib.md5(data).hexdigest()
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    del data  # release the exported view before buf goes away
    os.replace(tmp, real_path)
    with open(real_path + ".meta.tmp", "w") as f:
        json.dump({"md5": md5, **(meta or {})}, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(real_path + ".meta.tmp", real_path + ".meta")
    return real_path


def load_checkpoint(path: str, check_integrity: bool = True,
                    meta: Optional[dict] = None) -> Tuple[dict, dict, dict]:
    """(params, opt_flat, trainer_state) from one checkpoint file.

    ``meta``: the already-parsed ``.meta`` sidecar, when the caller has
    it in hand (``Checkpointer.restore``) — the integrity check then
    skips re-opening the sidecar."""
    real_path = path if path.endswith(".npz") else path + ".npz"
    # ONE read: the bytes the MD5 gate verifies are the very bytes the
    # arrays parse from — re-opening the file for np.load would let a
    # corruption landing between the two reads slip past the gate (and
    # pay the full-file I/O twice)
    with open(real_path, "rb") as f:
        raw = f.read()
    if check_integrity:
        if meta is None and os.path.exists(real_path + ".meta"):
            with open(real_path + ".meta") as f:
                meta = json.load(f)
        if meta is not None:
            md5 = hashlib.md5(raw).hexdigest()
            if md5 != meta.get("md5"):
                raise IOError(
                    f"checkpoint {real_path} failed MD5 integrity check"
                    " (WrongChecksum, go/pserver/service.go:49)")
    params = {}
    opt_flat = {}
    state = {}
    with np.load(io.BytesIO(raw)) as data:
        for k in data.files:
            if k.startswith("param::"):
                params[k[len("param::"):]] = data[k]
            elif k.startswith("opt::"):
                opt_flat[k[len("opt::"):]] = data[k]
            elif k.startswith("state::"):
                state[k[len("state::"):]] = data[k]
            elif k.startswith("stateobj::"):
                state[k[len("stateobj::"):]] = _loads_state(
                    data[k].tobytes())
    return params, opt_flat, state


def load_params(path: str, check_integrity: bool = True):
    params, opt_flat, _ = load_checkpoint(path, check_integrity)
    return params, opt_flat
