"""Training event objects delivered to user event handlers.

Mirror of ``python/paddle/v2/event.py``: BeginPass/EndPass,
BeginIteration/EndIteration, TestResult. The trainer calls
``event_handler(event)`` at the same points the reference does
(``python/paddle/v2/trainer.py:108-175``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional


class Event:
    pass


@dataclasses.dataclass
class BeginPass(Event):
    pass_id: int


@dataclasses.dataclass
class EndPass(Event):
    pass_id: int
    evaluator: Optional[Dict[str, float]] = None


@dataclasses.dataclass
class BeginIteration(Event):
    pass_id: int
    batch_id: int


@dataclasses.dataclass
class EndIteration(Event):
    pass_id: int
    batch_id: int
    cost: float
    evaluator: Optional[Dict[str, float]] = None


@dataclasses.dataclass
class TestResult(Event):
    pass_id: int
    cost: float
    evaluator: Optional[Dict[str, float]] = None
